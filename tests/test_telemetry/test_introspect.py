"""Introspection endpoint: Prometheus text-format golden, content type,
and the JSON surfaces (/healthz, /v1/phase, /v1/recorder)."""

import json
import urllib.request

import pytest

from sheeprl_tpu.telemetry import HUB
from sheeprl_tpu.telemetry.introspect import (
    PROMETHEUS_CONTENT_TYPE,
    IntrospectionServer,
    prometheus_name,
    prometheus_text,
)
from sheeprl_tpu.telemetry.recorder import RECORDER
from sheeprl_tpu.telemetry.spans import SPANS


class TestPrometheusText:
    def test_name_sanitization(self):
        assert prometheus_name("Compile/executables") == "sheeprl_compile_executables"
        assert prometheus_name("Phase/update.dispatch") == "sheeprl_phase_update_dispatch"
        assert prometheus_name("Sebulba/queue_depth") == "sheeprl_sebulba_queue_depth"

    def test_text_format_golden(self):
        """The exposition format is a scrape contract: one TYPE line per
        gauge, `name value` sample lines, sorted by key, trailing newline."""
        text = prometheus_text(
            {"Compile/executables": 3.0, "Phase/rollout": 0.25}
        )
        assert text == (
            "# TYPE sheeprl_compile_executables gauge\n"
            "sheeprl_compile_executables 3.0\n"
            "# TYPE sheeprl_phase_rollout gauge\n"
            "sheeprl_phase_rollout 0.25\n"
        )

    def test_empty_metrics_empty_body(self):
        assert prometheus_text({}) == ""

    def test_non_numeric_values_dropped(self):
        assert "nan" not in prometheus_text({"A/b": "not-a-number"})


@pytest.fixture()
def server():
    HUB.register("test_source", lambda: {"Test/metric": 1.5})
    srv = IntrospectionServer(port=0).start()
    yield srv
    srv.stop()
    HUB.unregister("test_source")


def get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read().decode()


class TestEndpoints:
    def test_healthz(self, server):
        status, ctype, body = get(server.url + "/healthz")
        assert status == 200
        assert ctype == "application/json"
        doc = json.loads(body)
        assert doc["ok"] is True
        assert "test_source" in doc["sources"]
        assert doc["pid"] > 0

    def test_metrics_content_type_and_body(self, server):
        status, ctype, body = get(server.url + "/metrics")
        assert status == 200
        assert ctype == PROMETHEUS_CONTENT_TYPE  # the golden scrape contract
        assert "# TYPE sheeprl_test_metric gauge" in body
        assert "sheeprl_test_metric 1.5" in body
        assert "sheeprl_telemetry_uptime_s" in body

    def test_metrics_scrape_is_non_destructive(self, server):
        with SPANS.span("rollout"):
            pass
        _, _, first = get(server.url + "/metrics")
        assert "sheeprl_phase_rollout" in first
        _, _, second = get(server.url + "/metrics")
        assert "sheeprl_phase_rollout" in second  # scrapes never roll windows

    def test_phase_breakdown(self, server):
        with SPANS.span("update.dispatch"):
            pass
        status, ctype, body = get(server.url + "/v1/phase")
        assert status == 200
        doc = json.loads(body)
        assert "update.dispatch" in doc["phases"]
        total = sum(p["frac"] for p in doc["phases"].values()) + doc["other_frac"]
        assert total == pytest.approx(1.0, abs=1e-4)

    def test_recorder_tail(self, server):
        for i in range(5):
            RECORDER.record("tick", i=i)
        status, _, body = get(server.url + "/v1/recorder?n=2")
        assert status == 200
        doc = json.loads(body)
        assert [e["i"] for e in doc["events"]] == [3, 4]
        assert doc["total"] >= 5

    def test_unknown_path_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            get(server.url + "/v1/nope")
        assert err.value.code == 404
