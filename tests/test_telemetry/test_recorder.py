"""Flight-recorder ring bounds + postmortem dumps, including the seeded
``env.step`` raise chaos drill (every chaos path leaves evidence) and the
final-metric-flush regression (buffered monitor counters must land even
when the loop dies mid-window)."""

import csv
import glob
import json
import os

import pytest

from sheeprl_tpu.resilience import faults
from sheeprl_tpu.resilience.faults import InjectedFault
from sheeprl_tpu.telemetry.recorder import RECORDER, SCHEMA, FlightRecorder


class TestRingBounds:
    def test_ring_keeps_newest_capacity_events(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("tick", i=i)
        assert len(rec) == 8
        kept = [e["i"] for e in rec.snapshot()]
        assert kept == list(range(12, 20))

    def test_snapshot_tail(self):
        rec = FlightRecorder(capacity=16)
        for i in range(10):
            rec.record("tick", i=i)
        assert [e["i"] for e in rec.snapshot(3)] == [7, 8, 9]

    def test_configure_resizes_preserving_newest(self):
        rec = FlightRecorder(capacity=16)
        for i in range(10):
            rec.record("tick", i=i)
        rec.configure({"capacity": 4})
        assert len(rec) == 4
        assert [e["i"] for e in rec.snapshot()] == [6, 7, 8, 9]

    def test_disabled_records_nothing(self):
        rec = FlightRecorder(capacity=4)
        rec.configure({"enabled": False})
        rec.record("tick")
        assert len(rec) == 0


class TestDump:
    def test_dump_writes_parseable_schema(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.configure({}, run_dir=str(tmp_path))
        rec.record("fault.injected", site="env.step", fault="raise")
        path = rec.dump("test-reason")
        assert path == str(tmp_path / "postmortem.json")
        with open(path) as f:
            doc = json.load(f)
        assert doc["schema"] == SCHEMA
        assert doc["reason"] == "test-reason"
        assert doc["pid"] == os.getpid()
        assert doc["monitors"] is not None and "resilience" in doc["monitors"]
        assert doc["phase_breakdown"] is not None
        kinds = [e["kind"] for e in doc["events"]]
        assert "fault.injected" in kinds

    def test_dump_without_run_dir_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        rec = FlightRecorder(capacity=8)
        rec.record("tick")
        assert rec.dump("no-home") is None
        assert not list(tmp_path.iterdir())

    def test_explicit_path_wins(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        target = tmp_path / "sub" / "pm.json"
        assert rec.dump("explicit", path=str(target)) == str(target)
        assert json.load(open(target))["reason"] == "explicit"


# env.step raises on its 10th invocation — mid-run, after compiles happened
# but (with metric.log_every sky-high) before ANY periodic metric flush
DRILL_PLAN = json.dumps(
    {"seed": 7, "plan": [{"site": "env.step", "kind": "raise", "at": 10}]}
)


def test_chaos_drill_leaves_postmortem_and_final_flush(tmp_path, monkeypatch):
    """A seeded ``env.step`` raise kills a real ``cli.run`` mid-training:
    the run dir must hold a parseable ``postmortem.json`` whose ring
    contains the injected-fault event AND a metrics file carrying the final
    ``Compile/*`` / ``Resilience/*`` flush (the finally-path hub flush —
    without it everything buffered since the last interval is lost)."""
    from sheeprl_tpu.cli import run

    monkeypatch.setenv(faults.ENV_VAR, DRILL_PLAN)
    try:
        with pytest.raises(InjectedFault):
            run(
                [
                    "exp=ppo",
                    "env=dummy",
                    "env.id=discrete_dummy",
                    "env.num_envs=2",
                    "env.sync_env=True",
                    "env.capture_video=False",
                    "algo.rollout_steps=8",
                    "algo.per_rank_batch_size=16",
                    "algo.update_epochs=1",
                    "algo.total_steps=128",
                    "algo.mlp_keys.encoder=[state]",
                    "algo.cnn_keys.encoder=[]",
                    "algo.run_test=False",
                    "fabric.devices=1",
                    "fabric.accelerator=cpu",
                    "checkpoint.every=0",
                    "checkpoint.save_last=False",
                    "buffer.memmap=False",
                    "metric.log_level=1",
                    "metric.log_every=1000000",  # NO periodic flush fires
                    "metric.logger.kind=csv",
                    f"log_dir={tmp_path}/logs",
                    "print_config=False",
                ]
            )
    finally:
        faults.clear_plan()

    run_dirs = glob.glob(f"{tmp_path}/logs/**/version_*", recursive=True)
    assert run_dirs, "the run never created its version dir"

    # 1) the postmortem: parseable, right reason, injected fault in the ring
    pm_path = os.path.join(run_dirs[0], "postmortem.json")
    assert os.path.isfile(pm_path), "crash exit left no postmortem.json"
    doc = json.load(open(pm_path))
    assert doc["schema"] == SCHEMA
    assert doc["reason"] == "exception"
    events = doc["events"]
    faults_seen = [e for e in events if e["kind"] == "fault.injected"]
    assert faults_seen and faults_seen[0]["site"] == "env.step"
    crashes = [e for e in events if e["kind"] == "crash"]
    assert crashes and "InjectedFault" in crashes[0]["error"]
    assert doc["monitors"]["resilience"]["injected"] >= 1

    # 2) the final flush: the ONLY metrics csv rows are the finally-path
    # hub flush (log_every was unreachable), and they carry the buffered
    # Compile/* and Resilience/* counters
    csv_path = os.path.join(run_dirs[0], "metrics.csv")
    assert os.path.isfile(csv_path)
    names = {row["name"] for row in csv.DictReader(open(csv_path))}
    assert "Compile/executables" in names
    assert "Resilience/faults_injected" in names
