"""Span nesting / aggregation math, the timer bridge, and fencing rules."""

import pytest

from sheeprl_tpu.telemetry import spans as spans_mod
from sheeprl_tpu.telemetry.spans import SPANS, TIMER_PHASES
from sheeprl_tpu.telemetry.tracer import TRACER


@pytest.fixture()
def clock(monkeypatch):
    """Deterministic span clock: tests advance ``clock['t']`` explicitly."""
    state = {"t": 0.0}
    monkeypatch.setattr(spans_mod, "_now", lambda: state["t"])
    SPANS.roll_window()  # window_start pinned at t=0
    return state


class TestNestingMath:
    def test_exclusive_time_subtracts_children(self, clock):
        outer = SPANS.push("rollout")
        clock["t"] = 1.0
        inner = SPANS.push("queue.wait")
        clock["t"] = 3.0
        SPANS.pop(inner)  # inner: 2s, all exclusive
        clock["t"] = 4.0
        SPANS.pop(outer)  # outer: 4s wall, 2s exclusive
        clock["t"] = 10.0
        bd = SPANS.breakdown()
        assert bd["window_s"] == 10.0
        assert bd["phases"]["queue.wait"]["seconds"] == 2.0
        assert bd["phases"]["rollout"]["seconds"] == 2.0
        assert bd["phases"]["queue.wait"]["frac"] == 0.2
        assert bd["phases"]["rollout"]["frac"] == 0.2
        assert bd["other_frac"] == 0.6

    def test_fractions_sum_to_one(self, clock):
        a = SPANS.push("update.dispatch")
        clock["t"] = 2.5
        SPANS.pop(a)
        clock["t"] = 4.0
        bd = SPANS.breakdown()
        total = sum(p["frac"] for p in bd["phases"].values()) + bd["other_frac"]
        assert total == pytest.approx(1.0, abs=1e-5)

    def test_overlapping_threads_normalize_past_wall(self, clock):
        """Σ exclusive beyond wall time (concurrent threads) still yields
        fractions summing to ~1.0 — normalization uses max(wall, Σ)."""
        # simulate two "threads" by accounting directly: one span of 8s and
        # another of 6s inside a 10s window
        a = SPANS.push("update.dispatch")
        clock["t"] = 8.0
        SPANS.pop(a)
        # second overlapping span: reuse the stack (sequential here, but
        # the accounting sums identically) — total tracked 14s > 10s wall
        clock["t"] = 4.0
        b = SPANS.push("ckpt.snapshot")
        clock["t"] = 10.0
        SPANS.pop(b)
        bd = SPANS.breakdown()
        total = sum(p["frac"] for p in bd["phases"].values()) + bd["other_frac"]
        assert total == pytest.approx(1.0, abs=1e-5)
        assert bd["other_frac"] == 0.0

    def test_leaked_children_close_with_parent(self, clock):
        outer = SPANS.push("rollout")
        clock["t"] = 1.0
        SPANS.push("queue.wait")  # never popped explicitly (e.g. a raise)
        clock["t"] = 3.0
        SPANS.pop(outer)  # unwinds the leaked child too
        bd = SPANS.breakdown()
        assert set(bd["phases"]) == {"rollout", "queue.wait"}
        assert SPANS.depth() == 0

    def test_counts_per_phase(self, clock):
        for _ in range(3):
            tok = SPANS.push("param.broadcast")
            clock["t"] += 1.0
            SPANS.pop(tok)
        assert SPANS.breakdown()["phases"]["param.broadcast"]["count"] == 3

    def test_roll_window_clears(self, clock):
        tok = SPANS.push("rollout")
        clock["t"] = 1.0
        SPANS.pop(tok)
        SPANS.roll_window()
        assert SPANS.breakdown()["phases"] == {}
        assert SPANS.metrics() == {}


class TestDisabled:
    def test_disabled_push_returns_none_and_pop_is_noop(self):
        SPANS.enabled = False
        token = SPANS.push("rollout")
        assert token is None
        SPANS.pop(token)
        assert SPANS.breakdown()["phases"] == {}

    def test_context_manager_disabled(self):
        SPANS.enabled = False
        with SPANS.span("update.dispatch"):
            pass
        assert SPANS.metrics() == {}


class TestTimerBridge:
    def test_timer_names_map_to_phases(self):
        assert TIMER_PHASES["Time/env_interaction_time"] == "rollout"
        assert TIMER_PHASES["Time/train_time"] == "update.dispatch"

    def test_timer_opens_spans_and_ticks_tracer(self):
        from sheeprl_tpu.utils.timer import timer

        ticks_before = TRACER.update_count
        timer.disabled = False
        with timer("Time/train_time"):
            pass
        with timer("Time/env_interaction_time"):
            pass
        metrics = SPANS.metrics()
        assert "Phase/update.dispatch" in metrics
        assert "Phase/rollout" in metrics
        assert TRACER.update_count == ticks_before + 1  # train dispatches only

    def test_timer_bridge_live_at_log_level_zero(self):
        """timer.disabled (metric.log_level=0) must NOT disable spans —
        bench runs rely on phase breakdowns with logging off."""
        from sheeprl_tpu.utils.timer import timer

        timer.to_dict(reset=True)  # drain leftovers from other tests
        timer.disabled = True
        try:
            with timer("Time/train_time"):
                pass
            assert "Phase/update.dispatch" in SPANS.metrics()
            assert timer.to_dict() == {}  # disabled timer recorded nothing
        finally:
            timer.disabled = False


class TestFencing:
    def test_fence_called_only_when_armed(self, monkeypatch):
        calls = []
        monkeypatch.setattr(SPANS, "_fence", lambda: calls.append(1))
        with SPANS.span("rollout"):
            pass
        assert not calls  # sync off, no trace window: no fence
        SPANS.sync = True
        with SPANS.span("rollout"):
            pass
        assert len(calls) == 2  # entry + exit
        SPANS.sync = False
        monkeypatch.setattr(TRACER, "active", True)
        with SPANS.span("rollout"):
            pass
        assert len(calls) == 4  # trace window armed → fenced again
