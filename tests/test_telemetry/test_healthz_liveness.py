"""/healthz liveness detail (ISSUE 14): last_update_age_s + stalled flag,
HTTP 503 when the update stream stalls — so the supervisor and k8s-style
probes can tell hung from healthy without killing blind."""

import json
import time
import urllib.error
import urllib.request

import pytest

from sheeprl_tpu.telemetry import SPANS, IntrospectionServer


def fetch_healthz(url):
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def tick_update():
    with SPANS.span("update.dispatch"):
        pass


class TestLiveness:
    def test_before_first_update_never_stalled(self):
        # warm-up compiles can take many minutes: a run that has not yet
        # completed an update is NOT stalled, however small the threshold
        with IntrospectionServer(stall_after_s=0.001) as srv:
            time.sleep(0.05)
            status, body = fetch_healthz(srv.url)
            assert status == 200
            assert body["ok"] is True and body["stalled"] is False
            assert body["last_update_age_s"] is None
            assert body["updates_done"] == 0

    def test_fresh_update_is_healthy(self):
        with IntrospectionServer(stall_after_s=30.0) as srv:
            tick_update()
            status, body = fetch_healthz(srv.url)
            assert status == 200 and body["stalled"] is False
            assert body["updates_done"] == 1
            assert 0.0 <= body["last_update_age_s"] < 30.0

    def test_stalled_run_answers_503(self):
        with IntrospectionServer(stall_after_s=0.1) as srv:
            tick_update()
            time.sleep(0.25)
            status, body = fetch_healthz(srv.url)
            assert status == 503
            assert body["ok"] is False and body["stalled"] is True
            assert body["last_update_age_s"] > 0.1
            # a new update clears the stall — hung vs slow is re-decided
            # per probe, never latched
            tick_update()
            status, body = fetch_healthz(srv.url)
            assert status == 200 and body["stalled"] is False
            assert body["updates_done"] == 2

    def test_detection_disabled_with_zero_threshold(self):
        with IntrospectionServer(stall_after_s=0.0) as srv:
            tick_update()
            time.sleep(0.05)
            status, body = fetch_healthz(srv.url)
            assert status == 200 and body["stalled"] is False

    def test_config_plumbs_threshold(self):
        # telemetry.setup_run wires telemetry.stall_after_s into the server
        from sheeprl_tpu import telemetry
        from sheeprl_tpu.utils.structured import dotdict

        cfg = dotdict(
            {"telemetry": {"stall_after_s": 7.5, "introspect": {"port": 0}}}
        )
        telemetry.setup_run(cfg, None)
        try:
            srv = telemetry.introspection_server()
            assert srv is not None and srv.stall_after_s == 7.5
        finally:
            telemetry.shutdown_run()

    def test_nested_dispatch_spans_do_not_tick(self):
        # only TOP-LEVEL update.dispatch spans are update completions (the
        # tracer's tick contract) — liveness must count the same stream
        before = SPANS.updates_done
        with SPANS.span("rollout"):
            with SPANS.span("update.dispatch"):
                pass
        assert SPANS.updates_done == before
