import pytest

from sheeprl_tpu.telemetry import HUB, RECORDER, SPANS, TRACER


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Every telemetry test starts from default knobs and empty windows.

    The monitors themselves are process-global cumulative counters shared
    with the rest of the suite — tests here assert DELTAS or register their
    own sources rather than resetting them."""
    SPANS.reset()
    RECORDER.clear()
    RECORDER.enabled = True
    RECORDER._run_dir = None
    TRACER.configure({}, None)
    HUB.reset()
    yield
    SPANS.reset()
    RECORDER.clear()
    RECORDER._run_dir = None
    TRACER.configure({}, None)
    HUB.reset()
    HUB.unregister("test_source")
