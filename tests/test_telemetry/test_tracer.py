"""Trace-window arming: update numbers, SHEEPRL_TRACE_AT, SIGUSR1."""

import os
import signal

import pytest

from sheeprl_tpu.telemetry.tracer import ENV_VAR, TraceScheduler


def make_scheduler(tmp_path, **tcfg):
    starts, stops = [], []
    sched = TraceScheduler(
        start_fn=lambda path: starts.append(path),
        stop_fn=lambda: stops.append(True),
    )
    sched.configure(tcfg, str(tmp_path))
    return sched, starts, stops


class TestUpdateNumberArming:
    def test_window_opens_and_closes_at_configured_updates(self, tmp_path):
        sched, starts, stops = make_scheduler(tmp_path, trace_at=[3], trace_updates=2)
        for _ in range(2):
            sched.tick()
        assert not starts and not sched.active
        sched.tick()  # update 3: window opens
        assert sched.active
        assert len(starts) == 1
        assert starts[0].endswith(os.path.join("trace", "update_000003"))
        sched.tick()  # update 4: still inside the 2-update window
        assert sched.active and not stops
        sched.tick()  # update 5: window closed before this dispatch
        assert not sched.active
        assert len(stops) == 1
        assert sched.windows_captured == 1

    def test_multiple_windows(self, tmp_path):
        sched, starts, stops = make_scheduler(
            tmp_path, trace_at=[2, 5], trace_updates=1
        )
        for _ in range(7):
            sched.tick()
        assert len(starts) == 2
        assert len(stops) == 2
        assert not sched.active

    def test_env_var_merges_with_config(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_VAR, "2, 4")
        sched, starts, _ = make_scheduler(tmp_path, trace_at=[6], trace_updates=1)
        for _ in range(7):
            sched.tick()
        assert len(starts) == 3  # 2 and 4 from the env, 6 from the config

    def test_malformed_env_var_warns_not_crashes(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_VAR, "not-a-number")
        with pytest.warns(RuntimeWarning):
            sched, starts, _ = make_scheduler(tmp_path, trace_at=[1], trace_updates=1)
        sched.tick()
        assert len(starts) == 1  # config arming still works

    def test_broken_profiler_never_kills_training(self, tmp_path):
        sched = TraceScheduler(
            start_fn=lambda path: (_ for _ in ()).throw(RuntimeError("no profiler")),
            stop_fn=lambda: None,
        )
        sched.configure({"trace_at": [1], "trace_updates": 1}, str(tmp_path))
        sched.tick()  # must not raise
        assert not sched.active

    def test_configure_resets_counter_and_closes_open_window(self, tmp_path):
        sched, starts, stops = make_scheduler(tmp_path, trace_at=[1], trace_updates=10)
        sched.tick()
        assert sched.active
        sched.configure({"trace_at": [1], "trace_updates": 1}, str(tmp_path))
        assert not sched.active and len(stops) == 1
        assert sched.update_count == 0
        sched.tick()  # re-arms: update numbers are per run
        assert len(starts) == 2


class TestSignalArming:
    def test_sigusr1_arms_one_window_at_next_tick(self, tmp_path):
        sched, starts, stops = make_scheduler(tmp_path, trace_updates=2)
        previous = signal.getsignal(signal.SIGUSR1)
        try:
            assert sched.install_signal()
            sched.tick()
            assert not starts  # nothing armed yet
            os.kill(os.getpid(), signal.SIGUSR1)
            sched.tick()  # the signal arms exactly one window
            assert sched.active
            assert len(starts) == 1
            sched.tick()
            sched.tick()
            assert not sched.active
            assert len(stops) == 1
            sched.tick()  # one-shot: no re-arm without a new signal
            assert len(starts) == 1
        finally:
            signal.signal(signal.SIGUSR1, previous)

    def test_request_is_the_programmatic_signal_spelling(self, tmp_path):
        sched, starts, _ = make_scheduler(tmp_path, trace_updates=1)
        sched.request()
        sched.tick()
        assert len(starts) == 1
