"""Test harness setup.

Mirrors the reference's test strategy (reference: tests/conftest.py:20-76 and
tests/test_algos/test_algos.py:16-53): multi-device coverage without real
hardware.  Here that means forcing the CPU XLA backend with 8 virtual devices
(``xla_force_host_platform_device_count``) *before* JAX initializes, so mesh /
sharding / collective code paths run everywhere.
"""

import os

# Must happen before any jax import anywhere in the test session.
# (On-chip golden validation does NOT go through pytest — COMMON pins
# fabric.accelerator=cpu — use `benchmarks/golden_drift.py --tpu`, which
# runs the same recipes against the real chip and writes DRIFT.md.)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) forces its own platform regardless of
# JAX_PLATFORMS; the config update below wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: multi-process / long-running tests")


@pytest.fixture(autouse=True)
def _restore_env():
    """Detect and undo environment-variable leaks between tests."""
    saved = dict(os.environ)
    yield
    for k in set(os.environ) - set(saved):
        del os.environ[k]
    for k, v in saved.items():
        if os.environ.get(k) != v:
            os.environ[k] = v


@pytest.fixture()
def tmp_logdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path
