"""Golden-value regression tests (numeric teeth for the train step).

One seeded end-to-end training iteration per algorithm family (PPO, SAC,
DreamerV3) through the real CLI on CPU fp32, with every logged loss compared
against committed expected values.  A sign or scale bug in GAE, KL balancing,
twin-Q, the entropy terms, etc. changes these numbers far beyond tolerance,
while the dry-run smokes (tests/test_algos/) would still pass.

Regenerate after an INTENDED numeric change with:

    GOLDEN_REGEN=1 python -m pytest tests/test_regression -q

then review the goldens.json diff like any other code change.
(Reference test strategy: SURVEY.md §4 — the reference has no numeric
regression layer either; this exceeds it deliberately.)
"""

import csv
import json
import os
from pathlib import Path

import pytest

from sheeprl_tpu.cli import run

GOLDENS_PATH = Path(__file__).parent / "goldens.json"

# Tolerance: same-platform CPU fp32 reruns are bit-identical; the slack is
# for XLA/jax version bumps.  A sign/scale bug moves losses by orders of
# magnitude more than this.
RTOL = 5e-3
ATOL = 1e-5

COMMON = [
    "dry_run=True",
    "seed=7",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "fabric.precision=32-true",
    "metric.log_level=1",
    "metric.log_every=1",
    "metric/logger=csv",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "algo.run_test=False",
    "print_config=False",
]

TINY_WM = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.learning_starts=0",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
]

FAMILIES = {
    "ppo": [
        "exp=ppo",
        "env.id=discrete_dummy",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
    ],
    "sac": [
        "exp=sac",
        "env.id=continuous_dummy",
        "algo.learning_starts=0",
        "algo.per_rank_batch_size=8",
        "algo.mlp_keys.encoder=[state]",
        "buffer.size=100",
    ],
    "dreamer_v3": [
        "exp=dreamer_v3",
        "env.id=discrete_dummy",
        "algo=dreamer_v3_XS",
        *TINY_WM,
        "algo.replay_ratio=1",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "env.screen_size=64",
        "env.max_episode_steps=20",
        "buffer.size=200",
    ],
}

# Every logged metric whose name contains one of these substrings is golden
# (state/grad metrics excluded: optimizer hyper-params may legitimately move).
GOLDEN_METRIC_SUBSTRINGS = ("Loss/", "State/kl", "State/post_entropy", "State/prior_entropy")


def _last_metrics(log_root: Path) -> dict:
    """Last logged value of each golden metric from the run's metrics.csv."""
    csvs = sorted(log_root.glob("**/metrics.csv"))
    assert csvs, f"no metrics.csv under {log_root}"
    out = {}
    with open(csvs[-1]) as f:
        for row in csv.DictReader(f):
            name = row.get("name", "")
            if any(s in name for s in GOLDEN_METRIC_SUBSTRINGS):
                out[name] = float(row["value"])
    return out


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_golden_train_step(tmp_path, family):
    run(COMMON + FAMILIES[family] + [f"log_dir={tmp_path}/logs"])
    got = _last_metrics(tmp_path)
    assert got, f"{family}: no golden metrics logged"

    goldens = json.loads(GOLDENS_PATH.read_text()) if GOLDENS_PATH.exists() else {}
    if os.environ.get("GOLDEN_REGEN"):
        goldens[family] = got
        GOLDENS_PATH.write_text(json.dumps(goldens, indent=2, sort_keys=True) + "\n")
        pytest.skip(f"regenerated goldens for {family}")

    assert family in goldens, f"no goldens for {family}; run with GOLDEN_REGEN=1"
    expected = goldens[family]
    assert set(got) == set(expected), (
        f"{family}: metric set changed: +{set(got) - set(expected)} -{set(expected) - set(got)}; "
        "regenerate goldens if intended"
    )
    for name, want in expected.items():
        have = got[name]
        assert have == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"{family}: {name} = {have!r}, golden {want!r} — numeric behavior changed; "
            "if intended, GOLDEN_REGEN=1 and review the diff"
        )
