"""Golden-value regression tests (numeric teeth for the train step).

One seeded end-to-end training iteration per algorithm family — ALL 14
registered entrypoints — through the real CLI on CPU fp32, with every
logged loss compared against committed expected values.  A sign or scale
bug in GAE, KL balancing, twin-Q, the entropy terms, etc. changes these
numbers far beyond tolerance, while the dry-run smokes (tests/test_algos/)
would still pass.

Regenerate after an INTENDED numeric change with:

    GOLDEN_REGEN=1 python -m pytest tests/test_regression -q

then review the goldens.json diff like any other code change.
(Reference test strategy: SURVEY.md §4 — the reference has no numeric
regression layer either; this exceeds it deliberately.  For the
cross-IMPLEMENTATION check against the reference's own loss math, see
test_reference_fixture.py.)
"""

import csv
import json
import os
import platform
from pathlib import Path

import pytest

from sheeprl_tpu.cli import run

GOLDENS_PATH = Path(__file__).parent / "goldens.json"

# Tolerance: same-platform CPU fp32 reruns are bit-identical; the slack is
# for XLA/jax version bumps.  A sign/scale bug moves losses by orders of
# magnitude more than this.
RTOL = 5e-3
ATOL = 1e-5
# On a platform/jax version differing from the one that captured the
# goldens, chaotic metrics (e.g. Loss/observation_loss ~4e3) can drift past
# RTOL without any code change (ADVICE r3): widen instead of flaking.
RTOL_FOREIGN = 5e-2
# Cancellation-prone metrics: a difference of O(k) constituents can show a
# large RELATIVE drift from ordinary platform numerics (DRIFT.md measured
# every sac_ae constituent at 3-5% on the real TPU; policy_loss = alpha*logp
# - min(Q) lands near zero, so that 3.5% becomes 62% relative).  A narrow,
# data-backed ABSOLUTE allowance per metric — never a blanket widening.
ATOL_FOREIGN = {
    "sac_ae:Loss/policy_loss": 0.1,
}


def _env_stamp() -> dict:
    import jax

    return {
        "jax": jax.__version__,
        "machine": platform.machine(),
        "system": platform.system(),
        # the backend IS part of the platform: TPU-vs-CPU drift is exactly
        # what RTOL_FOREIGN exists for (DRIFT.md second-platform table)
        "backend": jax.default_backend(),
    }

COMMON = [
    "dry_run=True",
    "seed=7",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "fabric.precision=32-true",
    "metric.log_level=1",
    "metric.log_every=1",
    "metric/logger=csv",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "algo.run_test=False",
    "print_config=False",
]

TINY_WM = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.learning_starts=0",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
]

_PPO_ARGS = [
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]

_SAC_ARGS = [
    "env.id=continuous_dummy",
    "algo.learning_starts=0",
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
    "buffer.size=100",
]

# Dreamer V1/V2 and the P2E pair share the tiny world-model sizing of the
# E2E smokes (tests/test_algos/test_algos.py) so goldens stay cheap.
_TINY_WM12 = [
    *TINY_WM,
    "algo.mlp_layers=1",
    "env.max_episode_steps=12",
    "buffer.size=400",
]

_P2E_ARGS = [
    "env.id=continuous_dummy",
    *_TINY_WM12,
    "algo.per_rank_pretrain_steps=0",
    "algo.ensembles.n=2",
]

FAMILIES = {
    "ppo": ["exp=ppo", "env.id=discrete_dummy", *_PPO_ARGS],
    "a2c": [
        "exp=a2c",
        "env.id=discrete_dummy",
        "algo.rollout_steps=8",
        "algo.mlp_keys.encoder=[state]",
    ],
    # single-process fallback topology: in-process player/trainer split
    "ppo_decoupled": ["exp=ppo_decoupled", "env.id=discrete_dummy", *_PPO_ARGS],
    "ppo_recurrent": [
        "exp=ppo_recurrent",
        "env.id=discrete_dummy",
        "env.mask_velocities=False",
        *_PPO_ARGS,
    ],
    "sac": ["exp=sac", *_SAC_ARGS],
    "sac_decoupled": ["exp=sac_decoupled", *_SAC_ARGS],
    "droq": ["exp=droq", *_SAC_ARGS],
    "sac_ae": [
        "exp=sac_ae",
        "env.id=continuous_dummy",
        "algo.per_rank_batch_size=4",
        "algo.learning_starts=0",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_channels_multiplier=4",
        "algo.hidden_size=32",
        "algo.encoder.features_dim=16",
        "env.screen_size=32",
        "env.max_episode_steps=16",
        "buffer.size=100",
    ],
    "dreamer_v1": [
        "exp=dreamer_v1",
        "env.id=continuous_dummy",
        *_TINY_WM12,
        "algo.world_model.stochastic_size=8",
    ],
    # EpisodeBuffer variant: the prioritize_ends sampling path feeds the
    # train step (VERDICT r3 #7's dv2 pixel golden)
    "dreamer_v2": [
        "exp=dreamer_v2",
        "env.id=discrete_dummy",
        *_TINY_WM12,
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "buffer.type=episode",
        "buffer.prioritize_ends=True",
    ],
    "dreamer_v3": [
        "exp=dreamer_v3",
        "env.id=discrete_dummy",
        "algo=dreamer_v3_XS",
        *TINY_WM,
        "algo.replay_ratio=1",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "env.screen_size=64",
        "env.max_episode_steps=20",
        "buffer.size=200",
    ],
    "p2e_dv1": [
        "exp=p2e_dv1_exploration",
        *_P2E_ARGS,
        "algo.world_model.stochastic_size=8",
    ],
    "p2e_dv2": [
        "exp=p2e_dv2_exploration",
        *_P2E_ARGS,
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
    ],
    "p2e_dv3": [
        "exp=p2e_dv3_exploration",
        "env.id=discrete_dummy",
        *_TINY_WM12,
        "algo.ensembles.n=3",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
    ],
}

# Every logged metric whose name contains one of these substrings is golden
# (state/grad metrics excluded: optimizer hyper-params may legitimately move).
GOLDEN_METRIC_SUBSTRINGS = ("Loss/", "State/kl", "State/post_entropy", "State/prior_entropy")


def _last_metrics(log_root: Path) -> dict:
    """Last logged value of each golden metric from the run's metrics.csv."""
    csvs = sorted(log_root.glob("**/metrics.csv"))
    assert csvs, f"no metrics.csv under {log_root}"
    out = {}
    with open(csvs[-1]) as f:
        for row in csv.DictReader(f):
            name = row.get("name", "")
            if any(s in name for s in GOLDEN_METRIC_SUBSTRINGS):
                out[name] = float(row["value"])
    return out


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_golden_train_step(tmp_path, family):
    run(COMMON + FAMILIES[family] + [f"log_dir={tmp_path}/logs"])
    got = _last_metrics(tmp_path)
    assert got, f"{family}: no golden metrics logged"

    goldens = json.loads(GOLDENS_PATH.read_text()) if GOLDENS_PATH.exists() else {}
    if os.environ.get("GOLDEN_REGEN"):
        goldens[family] = got
        # per-family stamp: regenerating ONE family must not re-label the
        # other 13 as captured on this platform/jax version
        env_stamps = goldens.setdefault("__env__", {})
        if not isinstance(env_stamps, dict) or "jax" in env_stamps:  # legacy global stamp
            env_stamps = goldens["__env__"] = {}
        env_stamps[family] = _env_stamp()
        GOLDENS_PATH.write_text(json.dumps(goldens, indent=2, sort_keys=True) + "\n")
        pytest.skip(f"regenerated goldens for {family}")

    assert family in goldens, f"no goldens for {family}; run with GOLDEN_REGEN=1"
    # foreign platform or jax version: widen tolerance instead of flaking
    # (chaotic metrics drift across XLA builds — ADVICE r3)
    rtol = RTOL
    stamps = goldens.get("__env__") or {}
    recorded_env = stamps.get(family) if isinstance(stamps, dict) and "jax" not in stamps else stamps
    if recorded_env is not None and "backend" not in recorded_env:
        recorded_env = {**recorded_env, "backend": "cpu"}  # legacy stamps: CPU-captured
    if recorded_env is not None and recorded_env != _env_stamp():
        rtol = RTOL_FOREIGN
        import warnings

        warnings.warn(
            f"goldens captured on {recorded_env}, running on {_env_stamp()}: "
            f"tolerance widened to rtol={rtol}"
        )
    expected = goldens[family]
    assert set(got) == set(expected), (
        f"{family}: metric set changed: +{set(got) - set(expected)} -{set(expected) - set(got)}; "
        "regenerate goldens if intended"
    )
    for name, want in expected.items():
        have = got[name]
        atol = ATOL
        if rtol == RTOL_FOREIGN:
            atol = max(ATOL, ATOL_FOREIGN.get(f"{family}:{name}", 0.0))
        assert have == pytest.approx(want, rel=rtol, abs=atol), (
            f"{family}: {name} = {have!r}, golden {want!r} — numeric behavior changed; "
            "if intended, GOLDEN_REGEN=1 and review the diff"
        )
