"""Generate reference_fixture.json: one seeded batch pushed through the
REFERENCE implementation's DreamerV3 world-model losses
(/root/reference/sheeprl/algos/dreamer_v3/loss.py:9-88 + its torch
distributions), recorded for the repo to assert against
(test_reference_fixture.py).

Goldens captured from the repo's own runs can only catch drift; this fixture
catches wrong-but-stable math — the loss values come from an independent
implementation (VERDICT r3 #4).

Run (needs /root/reference and torch, both present in the build image):

    python tests/test_regression/make_reference_fixture.py

and commit the refreshed JSON.  The inputs are stored in the fixture, so the
repo-side test never needs the reference tree or torch at test time.
"""

from __future__ import annotations

import importlib.util
import json
import pathlib
import sys
import types

import numpy as np

REFERENCE = pathlib.Path("/root/reference")
OUT = pathlib.Path(__file__).parent / "reference_fixture.json"

# tiny but non-degenerate shapes
T, B = 3, 2
CNN_SHAPE = (4, 4, 3)
MLP_DIM = 5
STOCH, DISCRETE = 4, 8
BINS = 255

KL_KWARGS = dict(kl_dynamic=0.5, kl_representation=0.1, kl_free_nats=1.0, kl_regularizer=1.0)
CONTINUE_SCALE = 1.0


def make_inputs() -> dict:
    rng = np.random.default_rng(42)
    f32 = lambda a: a.astype(np.float32)
    return {
        # reconstructions deliberately offset from targets so every loss term
        # is non-trivial; logits non-symmetric so KL(post, prior) != 0
        "cnn_target": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "cnn_recon": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "mlp_target": f32(rng.normal(0, 2.0, (T, B, MLP_DIM))),
        "mlp_recon": f32(rng.normal(0, 2.0, (T, B, MLP_DIM))),
        "reward_logits": f32(rng.normal(0, 1.0, (T, B, BINS))),
        "rewards": f32(rng.normal(0, 1.5, (T, B))),
        "continue_logits": f32(rng.normal(0, 1.0, (T, B))),
        "terminated": f32(rng.integers(0, 2, (T, B))),
        "posterior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
        "prior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
    }


def load_reference_oracle():
    """Import the reference loss + distribution modules standalone: the
    package __init__ chains optional deps (dotenv, lightning) this image
    lacks, and only symlog/symexp are actually needed from its utils."""
    import torch

    sys.path.insert(0, str(REFERENCE))
    for name in ("sheeprl", "sheeprl.utils", "sheeprl.algos", "sheeprl.algos.dreamer_v3"):
        pkg = types.ModuleType(name)
        pkg.__path__ = [str(REFERENCE / name.replace(".", "/"))]
        sys.modules[name] = pkg
    uu = types.ModuleType("sheeprl.utils.utils")
    uu.symlog = lambda x: torch.sign(x) * torch.log1p(torch.abs(x))
    uu.symexp = lambda x: torch.sign(x) * (torch.exp(torch.abs(x)) - 1)
    sys.modules["sheeprl.utils.utils"] = uu

    def load(name, path):
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod

    dist = load("sheeprl.utils.distribution", REFERENCE / "sheeprl/utils/distribution.py")
    loss = load("sheeprl.algos.dreamer_v3.loss", REFERENCE / "sheeprl/algos/dreamer_v3/loss.py")
    return dist, loss


def main() -> None:
    import torch
    from torch.distributions import Independent

    dist, loss_mod = load_reference_oracle()
    inp = make_inputs()
    t = {k: torch.from_numpy(v) for k, v in inp.items()}

    po = {
        "rgb": dist.MSEDistribution(t["cnn_recon"], dims=len(CNN_SHAPE)),
        "state": dist.SymlogDistribution(t["mlp_recon"], dims=1),
    }
    observations = {"rgb": t["cnn_target"], "state": t["mlp_target"]}
    pr = dist.TwoHotEncodingDistribution(t["reward_logits"], dims=1)
    pc = Independent(dist.BernoulliSafeMode(logits=t["continue_logits"][..., None]), 1)
    continue_targets = (1.0 - t["terminated"])[..., None]

    rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = (
        loss_mod.reconstruction_loss(
            po=po,
            observations=observations,
            pr=pr,
            rewards=t["rewards"][..., None],
            priors_logits=t["prior_logits"],
            posteriors_logits=t["posterior_logits"],
            pc=pc,
            continue_targets=continue_targets,
            continue_scale_factor=CONTINUE_SCALE,
            **KL_KWARGS,
        )
    )

    fixture = {
        "meta": {
            "source": "sheeprl/algos/dreamer_v3/loss.py:9-88 (reference implementation)",
            "shapes": {"T": T, "B": B, "cnn": CNN_SHAPE, "mlp": MLP_DIM,
                       "stoch": STOCH, "discrete": DISCRETE, "bins": BINS},
            "kl_kwargs": KL_KWARGS,
            "continue_scale_factor": CONTINUE_SCALE,
        },
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "expected": {
            "world_model_loss": float(rec_loss),
            "kl": float(kl),
            "state_loss": float(state_loss),
            "reward_loss": float(reward_loss),
            "observation_loss": float(observation_loss),
            "continue_loss": float(continue_loss),
        },
    }
    OUT.write_text(json.dumps(fixture) + "\n")
    print(f"wrote {OUT} — expected: {fixture['expected']}")


if __name__ == "__main__":
    main()
