"""Generate reference_fixture.json: one seeded batch pushed through the
REFERENCE implementation's DreamerV3 world-model losses
(/root/reference/sheeprl/algos/dreamer_v3/loss.py:9-88 + its torch
distributions), recorded for the repo to assert against
(test_reference_fixture.py).

Goldens captured from the repo's own runs can only catch drift; this fixture
catches wrong-but-stable math — the loss values come from an independent
implementation (VERDICT r3 #4).

Run (needs /root/reference and torch, both present in the build image):

    python tests/test_regression/make_reference_fixture.py

and commit the refreshed JSON.  The inputs are stored in the fixture, so the
repo-side test never needs the reference tree or torch at test time.
"""

from __future__ import annotations

import importlib.util
import json
import pathlib
import sys
import types

import numpy as np

REFERENCE = pathlib.Path("/root/reference")
OUT = pathlib.Path(__file__).parent / "reference_fixture.json"

# tiny but non-degenerate shapes
T, B = 3, 2
CNN_SHAPE = (4, 4, 3)
MLP_DIM = 5
STOCH, DISCRETE = 4, 8
BINS = 255

KL_KWARGS = dict(kl_dynamic=0.5, kl_representation=0.1, kl_free_nats=1.0, kl_regularizer=1.0)
CONTINUE_SCALE = 1.0


def make_inputs() -> dict:
    rng = np.random.default_rng(42)
    f32 = lambda a: a.astype(np.float32)
    return {
        # reconstructions deliberately offset from targets so every loss term
        # is non-trivial; logits non-symmetric so KL(post, prior) != 0
        "cnn_target": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "cnn_recon": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "mlp_target": f32(rng.normal(0, 2.0, (T, B, MLP_DIM))),
        "mlp_recon": f32(rng.normal(0, 2.0, (T, B, MLP_DIM))),
        "reward_logits": f32(rng.normal(0, 1.0, (T, B, BINS))),
        "rewards": f32(rng.normal(0, 1.5, (T, B))),
        "continue_logits": f32(rng.normal(0, 1.0, (T, B))),
        "terminated": f32(rng.integers(0, 2, (T, B))),
        "posterior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
        "prior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
    }


def load_reference_oracle():
    """Import the reference loss + distribution modules standalone: the
    package __init__ chains optional deps (dotenv, lightning) this image
    lacks, and only symlog/symexp are actually needed from its utils."""
    import torch

    sys.path.insert(0, str(REFERENCE))
    for name in ("sheeprl", "sheeprl.utils", "sheeprl.algos", "sheeprl.algos.dreamer_v3"):
        pkg = types.ModuleType(name)
        pkg.__path__ = [str(REFERENCE / name.replace(".", "/"))]
        sys.modules[name] = pkg
    uu = types.ModuleType("sheeprl.utils.utils")
    uu.symlog = lambda x: torch.sign(x) * torch.log1p(torch.abs(x))
    uu.symexp = lambda x: torch.sign(x) * (torch.exp(torch.abs(x)) - 1)
    sys.modules["sheeprl.utils.utils"] = uu

    def load(name, path):
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod

    dist = load("sheeprl.utils.distribution", REFERENCE / "sheeprl/utils/distribution.py")
    loss = load("sheeprl.algos.dreamer_v3.loss", REFERENCE / "sheeprl/algos/dreamer_v3/loss.py")
    return dist, loss


def load_ref_module(name: str, rel: str):
    """Load a pure-torch reference loss module standalone."""
    spec = importlib.util.spec_from_file_location(name, REFERENCE / rel)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def make_ppo_section() -> dict:
    """PPO clipped-surrogate / value / entropy losses through the reference
    (reference: sheeprl/algos/ppo/loss.py:1-75)."""
    import torch

    ppo_loss = load_ref_module("ref_ppo_loss", "sheeprl/algos/ppo/loss.py")
    rng = np.random.default_rng(7)
    n = 32
    inp = {
        "new_logprobs": rng.normal(-1.0, 0.5, n).astype(np.float32),
        "old_logprobs": rng.normal(-1.0, 0.5, n).astype(np.float32),
        "advantages": rng.normal(0.0, 1.0, n).astype(np.float32),
        "new_values": rng.normal(0.0, 1.0, n).astype(np.float32),
        "old_values": rng.normal(0.0, 1.0, n).astype(np.float32),
        "returns": rng.normal(0.0, 1.0, n).astype(np.float32),
        "entropy": rng.uniform(0.1, 1.5, n).astype(np.float32),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    clip = 0.2
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "clip_coef": clip,
        "expected": {
            "policy_loss": float(ppo_loss.policy_loss(t["new_logprobs"], t["old_logprobs"], t["advantages"], clip)),
            "value_loss_unclipped": float(ppo_loss.value_loss(t["new_values"], t["old_values"], t["returns"], clip, False)),
            "value_loss_clipped": float(ppo_loss.value_loss(t["new_values"], t["old_values"], t["returns"], clip, True)),
            "entropy_loss": float(ppo_loss.entropy_loss(t["entropy"])),
        },
    }


def make_sac_section() -> dict:
    """SAC critic / actor / temperature losses through the reference
    (reference: sheeprl/algos/sac/loss.py:1-27)."""
    import torch

    sac_loss = load_ref_module("ref_sac_loss", "sheeprl/algos/sac/loss.py")
    rng = np.random.default_rng(11)
    n, num_critics = 32, 2
    inp = {
        "qf_values": rng.normal(0.0, 1.0, (n, num_critics)).astype(np.float32),
        "next_qf_value": rng.normal(0.0, 1.0, (n, 1)).astype(np.float32),
        "logprobs": rng.normal(-1.0, 0.5, (n, 1)).astype(np.float32),
        "min_q": rng.normal(0.0, 1.0, (n, 1)).astype(np.float32),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    alpha, log_alpha, target_entropy = 0.2, float(np.log(0.2)), -3.0
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "alpha": alpha,
        "log_alpha": log_alpha,
        "target_entropy": target_entropy,
        "num_critics": num_critics,
        "expected": {
            "critic_loss": float(sac_loss.critic_loss(t["qf_values"], t["next_qf_value"], num_critics)),
            "policy_loss": float(sac_loss.policy_loss(alpha, t["logprobs"], t["min_q"])),
            "entropy_loss": float(
                sac_loss.entropy_loss(torch.tensor(log_alpha), t["logprobs"], torch.tensor(target_entropy))
            ),
        },
    }


def make_a2c_section() -> dict:
    """A2C policy loss through the reference (reference:
    sheeprl/algos/a2c/loss.py:1-40; its value loss is PPO's, covered above —
    recorded here under A2C's 'sum' reduction as used by its config)."""
    import torch

    a2c_loss = load_ref_module("ref_a2c_loss", "sheeprl/algos/a2c/loss.py")
    ppo_loss = load_ref_module("ref_ppo_loss2", "sheeprl/algos/ppo/loss.py")
    rng = np.random.default_rng(13)
    n = 32
    inp = {
        "logprobs": rng.normal(-1.0, 0.5, n).astype(np.float32),
        "advantages": rng.normal(0.0, 1.0, n).astype(np.float32),
        "values": rng.normal(0.0, 1.0, n).astype(np.float32),
        "returns": rng.normal(0.0, 1.0, n).astype(np.float32),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "expected": {
            "policy_loss_sum": float(a2c_loss.policy_loss(t["logprobs"], t["advantages"], "sum")),
            "policy_loss_mean": float(a2c_loss.policy_loss(t["logprobs"], t["advantages"], "mean")),
            "value_loss_sum": float(
                ppo_loss.value_loss(t["values"], t["values"], t["returns"], 0.2, False, "sum")
            ),
        },
    }


def make_dv1_section() -> dict:
    """DreamerV1 reconstruction loss through the reference
    (reference: sheeprl/algos/dreamer_v1/loss.py:41-95) — Gaussian
    unit-variance obs/reward heads and a diagonal-Gaussian state KL with
    free nats.  Continue head disabled, matching the shipped default
    (reference: configs/algo/dreamer_v1.yaml use_continues: False; the
    reference's continue term also carries a sign quirk documented in
    sheeprl_tpu/algos/dreamer_v1/loss.py)."""
    import torch
    from torch.distributions import Bernoulli, Independent, Normal

    dv1_loss = load_ref_module("ref_dv1_loss", "sheeprl/algos/dreamer_v1/loss.py")
    rng = np.random.default_rng(17)
    S = 6
    f32 = lambda a: a.astype(np.float32)
    inp = {
        "cnn_target": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "cnn_recon": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "mlp_target": f32(rng.normal(0, 1.0, (T, B, MLP_DIM))),
        "mlp_recon": f32(rng.normal(0, 1.0, (T, B, MLP_DIM))),
        "reward_mean": f32(rng.normal(0, 1.0, (T, B))),
        "rewards": f32(rng.normal(0, 1.0, (T, B))),
        "post_mean": f32(rng.normal(0, 1.0, (T, B, S))),
        "post_std": f32(rng.uniform(0.2, 1.5, (T, B, S))),
        "prior_mean": f32(rng.normal(0, 1.0, (T, B, S))),
        "prior_std": f32(rng.uniform(0.2, 1.5, (T, B, S))),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    kl_free_nats, kl_regularizer = 3.0, 1.0
    qo = {
        "rgb": Independent(Normal(t["cnn_recon"], 1.0), len(CNN_SHAPE)),
        "state": Independent(Normal(t["mlp_recon"], 1.0), 1),
    }
    observations = {"rgb": t["cnn_target"], "state": t["mlp_target"]}
    qr = Normal(t["reward_mean"], 1.0)
    rec, kl, state_loss, reward_loss, observation_loss, continue_loss = dv1_loss.reconstruction_loss(
        qo, observations, qr, t["rewards"],
        Independent(Normal(t["post_mean"], t["post_std"]), 1),
        Independent(Normal(t["prior_mean"], t["prior_std"]), 1),
        kl_free_nats=kl_free_nats, kl_regularizer=kl_regularizer,
    )
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "kl_free_nats": kl_free_nats,
        "kl_regularizer": kl_regularizer,
        "expected": {
            "reconstruction_loss": float(rec),
            "kl": float(kl),
            "state_loss": float(state_loss),
            "reward_loss": float(reward_loss),
            "observation_loss": float(observation_loss),
        },
    }


def make_dv2_section() -> dict:
    """DreamerV2 reconstruction loss through the reference
    (reference: sheeprl/algos/dreamer_v2/loss.py:9-85) — α-balanced
    categorical KL (free-avg), Gaussian heads, Bernoulli discount head."""
    import torch
    from torch.distributions import Bernoulli, Independent, Normal

    dv2_loss = load_ref_module("ref_dv2_loss", "sheeprl/algos/dreamer_v2/loss.py")
    rng = np.random.default_rng(19)
    f32 = lambda a: a.astype(np.float32)
    inp = {
        "cnn_target": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "cnn_recon": f32(rng.uniform(-0.5, 0.5, (T, B) + CNN_SHAPE)),
        "mlp_target": f32(rng.normal(0, 1.0, (T, B, MLP_DIM))),
        "mlp_recon": f32(rng.normal(0, 1.0, (T, B, MLP_DIM))),
        "reward_mean": f32(rng.normal(0, 1.0, (T, B))),
        "rewards": f32(rng.normal(0, 1.0, (T, B))),
        "posterior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
        "prior_logits": f32(rng.normal(0, 1.0, (T, B, STOCH, DISCRETE))),
        "continue_logits": f32(rng.normal(0, 1.0, (T, B))),
        "terminated": f32(rng.integers(0, 2, (T, B))),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    alpha, free_nats, regularizer, gamma, discount_scale = 0.8, 1.0, 1.0, 0.99, 1.0
    po = {
        "rgb": Independent(Normal(t["cnn_recon"], 1.0), len(CNN_SHAPE)),
        "state": Independent(Normal(t["mlp_recon"], 1.0), 1),
    }
    observations = {"rgb": t["cnn_target"], "state": t["mlp_target"]}
    pr = Normal(t["reward_mean"], 1.0)
    # the reference trains with global arg-validation off (its cli disables
    # it); the (1-terminated)*gamma "soft" targets require that here too
    pc = Independent(Bernoulli(logits=t["continue_logits"][..., None], validate_args=False), 1,
                     validate_args=False)
    continue_targets = ((1.0 - t["terminated"]) * gamma)[..., None]
    rec, kl, kl_loss, reward_loss, observation_loss, continue_loss = dv2_loss.reconstruction_loss(
        po, observations, pr, t["rewards"], t["prior_logits"], t["posterior_logits"],
        kl_balancing_alpha=alpha, kl_free_nats=free_nats, kl_free_avg=True,
        kl_regularizer=regularizer, pc=pc, continue_targets=continue_targets,
        discount_scale_factor=discount_scale,
    )
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "kl_balancing_alpha": alpha,
        "kl_free_nats": free_nats,
        "kl_regularizer": regularizer,
        "gamma": gamma,
        "discount_scale_factor": discount_scale,
        "expected": {
            "reconstruction_loss": float(rec),
            "kl": float(kl.mean()),
            "kl_loss": float(kl_loss),
            "reward_loss": float(reward_loss),
            "observation_loss": float(observation_loss),
            "continue_loss": float(continue_loss),
        },
    }


def load_ref_functions(rel: str, names: tuple, extra_ns: dict) -> dict:
    """Compile ONLY the named top-level functions/classes out of a reference
    file — sidesteps module-level imports (lightning, omegaconf, rich) this
    image lacks.  The bodies use only what ``extra_ns`` provides."""
    import ast

    src = (REFERENCE / rel).read_text()
    tree = ast.parse(src)
    wanted = [
        n for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.ClassDef)) and n.name in names
    ]
    assert len(wanted) == len(names), f"missing definitions in {rel}"
    ns = dict(extra_ns)
    for node in wanted:
        node.decorator_list = []  # e.g. @torch.no_grad()
        mod = ast.Module(body=[node], type_ignores=[])
        exec(compile(ast.fix_missing_locations(mod), rel, "exec"), ns)
    return {n: ns[n] for n in names}


def make_math_section() -> dict:
    """Core math utilities through the reference: GAE
    (reference: sheeprl/utils/utils.py:63-100), TD(λ)
    (reference: sheeprl/algos/dreamer_v3/utils.py:66-77), the two-hot
    codec (reference: sheeprl/utils/utils.py:156-205), and three steps of
    TF-style RMSprop (reference: sheeprl/optim/rmsprop_tf.py)."""
    import torch
    from typing import Optional, Tuple

    ns = {"torch": torch, "Tensor": torch.Tensor, "Optional": Optional, "Tuple": Tuple}
    fns = load_ref_functions(
        "sheeprl/utils/utils.py", ("gae", "two_hot_encoder", "two_hot_decoder"), ns
    )
    lam = load_ref_functions(
        "sheeprl/algos/dreamer_v3/utils.py", ("compute_lambda_values",), ns
    )["compute_lambda_values"]

    rng = np.random.default_rng(29)
    Tn, Bn = 7, 3
    f32 = lambda a: a.astype(np.float32)
    inp = {
        "rewards": f32(rng.normal(0, 1.0, (Tn, Bn))),
        "values": f32(rng.normal(0, 1.0, (Tn, Bn))),
        "dones": f32(rng.integers(0, 2, (Tn, Bn))),
        "next_value": f32(rng.normal(0, 1.0, (1, Bn))),
        "lam_rewards": f32(rng.normal(0, 1.0, (Tn, Bn, 1))),
        "lam_values": f32(rng.normal(0, 1.0, (Tn, Bn, 1))),
        "lam_continues": f32(rng.uniform(0, 1, (Tn, Bn, 1))),
        "two_hot_x": f32(rng.uniform(-19.0, 19.0, (Bn, 1))),
        "two_hot_probs": f32(rng.dirichlet(np.ones(11), Bn)),
        "opt_param": f32(rng.normal(0, 1.0, (4, 3))),
        "opt_grads": f32(rng.normal(0, 0.5, (3, 4, 3))),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    gamma, gae_lambda, lmbda = 0.99, 0.95, 0.95
    returns, advantages = fns["gae"](
        t["rewards"], t["values"], t["dones"].bool(), t["next_value"], Tn, gamma, gae_lambda
    )
    lambda_values = lam(t["lam_rewards"], t["lam_values"], t["lam_continues"], lmbda)
    support, buckets = 5, 11
    encoded = fns["two_hot_encoder"](t["two_hot_x"], support, buckets)
    decoded = fns["two_hot_decoder"](t["two_hot_probs"], support)

    # 3 RMSpropTF steps on a seeded param with momentum (constant lr; the
    # reference's lr_in_momentum only differs under a mid-run lr change)
    # Ratio governor: reference law over a mixed call sequence, including
    # the pretrain clamp and fractional-carry behavior
    import warnings as _w

    RefRatio = load_ref_functions(
        "sheeprl/utils/utils.py", ("Ratio",),
        {"warnings": _w, "Dict": dict, "Any": object, "Mapping": dict},
    )["Ratio"]
    ratio_cases = []
    for ratio, pretrain, calls in [
        (0.5, 0, [1, 2, 3, 10, 100, 101]),
        (1.0, 7, [4, 10, 20]),
        (0.0625, 1024, [2048, 2052, 2112, 4096]),
        (2.0, 0, [3, 4, 10]),
    ]:
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            r = RefRatio(ratio, pretrain_steps=pretrain)
            ratio_cases.append({
                "ratio": ratio, "pretrain_steps": pretrain, "calls": calls,
                "expected": [int(r(c)) for c in calls],
            })

    rmsprop_mod = load_ref_module("ref_rmsprop_tf", "sheeprl/optim/rmsprop_tf.py")
    lr, alpha, eps, momentum = 0.05, 0.9, 1e-10, 0.9
    p = torch.nn.Parameter(t["opt_param"].clone())
    opt = rmsprop_mod.RMSpropTF([p], lr=lr, alpha=alpha, eps=eps, momentum=momentum)
    for i in range(3):
        opt.zero_grad()
        p.grad = t["opt_grads"][i].clone()
        opt.step()

    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "gamma": gamma,
        "gae_lambda": gae_lambda,
        "lmbda": lmbda,
        "two_hot_support": support,
        "two_hot_buckets": buckets,
        "rmsprop": {"lr": lr, "alpha": alpha, "eps": eps, "momentum": momentum},
        "ratio_cases": ratio_cases,
        "expected": {
            "returns": returns.tolist(),
            "advantages": advantages.tolist(),
            "lambda_values": lambda_values.tolist(),
            "two_hot_encoded": encoded.tolist(),
            "two_hot_decoded": decoded.tolist(),
            "rmsprop_param_after_3_steps": p.detach().tolist(),
        },
    }


def make_truncnorm_section() -> dict:
    """TruncatedNormal([-1,1]) — the DV1/DV2 continuous-action policy
    distribution — log_prob / mean / entropy through the reference
    (reference: sheeprl/utils/distribution.py:25-150)."""
    import torch

    dist, _ = load_reference_oracle()
    rng = np.random.default_rng(31)
    n = 16
    inp = {
        "loc": rng.uniform(-1.5, 1.5, n).astype(np.float32),
        "scale": rng.uniform(0.1, 1.2, n).astype(np.float32),
        "value": rng.uniform(-0.99, 0.99, n).astype(np.float32),
    }
    t = {k: torch.from_numpy(v) for k, v in inp.items()}
    d = dist.TruncatedNormal(t["loc"], t["scale"], -1.0, 1.0)
    return {
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "expected": {
            "log_prob": d.log_prob(t["value"]).tolist(),
            "mean": d.mean.tolist(),
            "entropy": d.entropy().tolist(),
        },
    }


def make_sac_ae_section() -> dict:
    """SAC-AE decoder target preprocessing through the reference
    (reference: sheeprl/algos/sac_ae/utils.py:68-76 — 5-bit quantization +
    uniform dither).  The dither is stochastic, so it is zeroed on both
    sides; the deterministic quantization grid is what a transcription
    error would break."""
    import torch

    fns = load_ref_functions(
        "sheeprl/algos/sac_ae/utils.py", ("preprocess_obs",),
        {"torch": torch, "Tensor": torch.Tensor},
    )
    rng = np.random.default_rng(37)
    raw = rng.integers(0, 256, (2, 8, 8, 3)).astype(np.float32)
    orig_rand = torch.rand_like
    torch.rand_like = lambda t: torch.zeros_like(t)
    try:
        expected = fns["preprocess_obs"](torch.from_numpy(raw), bits=5)
    finally:
        torch.rand_like = orig_rand
    return {
        "inputs": {"raw": raw.tolist()},
        "bits": 5,
        "expected": {"target": expected.tolist()},
    }


def make_p2e_section() -> dict:
    """Plan2Explore intrinsic reward through the reference expression
    (reference: sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py:283 —
    ``next_state_embedding.var(0).mean(-1) * multiplier``; torch's ``var``
    is UNBIASED (N-1), which jnp.var is not by default)."""
    import torch

    rng = np.random.default_rng(23)
    n_ens, H, n, D = 5, 4, 6, 8
    preds = rng.normal(0, 1.0, (n_ens, H, n, D)).astype(np.float32)
    mult = 0.5
    expected = (torch.from_numpy(preds).var(0).mean(-1) * mult).numpy()
    return {
        "inputs": {"preds": preds.tolist()},
        "multiplier": mult,
        "expected": {"intrinsic_reward": expected.tolist()},
    }


def main() -> None:
    import torch
    from torch.distributions import Independent

    dist, loss_mod = load_reference_oracle()
    inp = make_inputs()
    t = {k: torch.from_numpy(v) for k, v in inp.items()}

    po = {
        "rgb": dist.MSEDistribution(t["cnn_recon"], dims=len(CNN_SHAPE)),
        "state": dist.SymlogDistribution(t["mlp_recon"], dims=1),
    }
    observations = {"rgb": t["cnn_target"], "state": t["mlp_target"]}
    pr = dist.TwoHotEncodingDistribution(t["reward_logits"], dims=1)
    pc = Independent(dist.BernoulliSafeMode(logits=t["continue_logits"][..., None]), 1)
    continue_targets = (1.0 - t["terminated"])[..., None]

    rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = (
        loss_mod.reconstruction_loss(
            po=po,
            observations=observations,
            pr=pr,
            rewards=t["rewards"][..., None],
            priors_logits=t["prior_logits"],
            posteriors_logits=t["posterior_logits"],
            pc=pc,
            continue_targets=continue_targets,
            continue_scale_factor=CONTINUE_SCALE,
            **KL_KWARGS,
        )
    )

    fixture = {
        "ppo": make_ppo_section(),
        "sac": make_sac_section(),
        "a2c": make_a2c_section(),
        "dreamer_v1": make_dv1_section(),
        "dreamer_v2": make_dv2_section(),
        "p2e": make_p2e_section(),
        "math": make_math_section(),
        "truncated_normal": make_truncnorm_section(),
        "sac_ae": make_sac_ae_section(),
        "meta": {
            "source": "sheeprl/algos/dreamer_v3/loss.py:9-88 (reference implementation)",
            "shapes": {"T": T, "B": B, "cnn": CNN_SHAPE, "mlp": MLP_DIM,
                       "stoch": STOCH, "discrete": DISCRETE, "bins": BINS},
            "kl_kwargs": KL_KWARGS,
            "continue_scale_factor": CONTINUE_SCALE,
        },
        "inputs": {k: v.tolist() for k, v in inp.items()},
        "expected": {
            "world_model_loss": float(rec_loss),
            "kl": float(kl),
            "state_loss": float(state_loss),
            "reward_loss": float(reward_loss),
            "observation_loss": float(observation_loss),
            "continue_loss": float(continue_loss),
        },
    }
    OUT.write_text(json.dumps(fixture) + "\n")
    print(f"wrote {OUT} — expected: {fixture['expected']}")


if __name__ == "__main__":
    main()
