"""Cross-IMPLEMENTATION loss check (VERDICT r3 #4).

`reference_fixture.json` holds one seeded batch and the loss values the
REFERENCE implementation (torch, /root/reference/sheeprl/algos/dreamer_v3/
loss.py:9-88) computed for it — regenerate with make_reference_fixture.py.
Here the repo's jax implementation consumes the SAME batch and must land on
the SAME numbers in fp32.  Unlike the self-captured goldens
(test_golden.py), a pass here means the math agrees with an independent
implementation, not merely with yesterday's self.

Covers in one batch: MSE pixel reconstruction, symlog vector
reconstruction, two-hot symlog reward NLL, Bernoulli continue NLL, and the
free-nats-clipped balanced categorical KL.
"""

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

FIXTURE_PATH = Path(__file__).parent / "reference_fixture.json"

# fp32 accumulation-order slack between XLA and torch
RTOL = 2e-5
ATOL = 1e-6


@pytest.fixture(scope="module")
def fixture():
    assert FIXTURE_PATH.exists(), "run make_reference_fixture.py (needs /root/reference)"
    return json.loads(FIXTURE_PATH.read_text())


def test_world_model_losses_match_reference(fixture):
    from sheeprl_tpu.algos.dreamer_v3.loss import world_model_loss
    from sheeprl_tpu.utils.distribution import (
        Bernoulli,
        MSEDistribution,
        SymlogDistribution,
        TwoHotEncodingDistribution,
    )

    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in fixture["inputs"].items()}
    meta = fixture["meta"]

    obs_log_probs = {
        "rgb": MSEDistribution(inp["cnn_recon"], event_dims=len(meta["shapes"]["cnn"])).log_prob(
            inp["cnn_target"]
        ),
        "state": SymlogDistribution(inp["mlp_recon"], event_dims=1).log_prob(inp["mlp_target"]),
    }
    reward_lp = TwoHotEncodingDistribution(inp["reward_logits"], dims=1).log_prob(
        inp["rewards"][..., None]
    )
    cont_lp = Bernoulli(inp["continue_logits"], event_dims=0).log_prob(1.0 - inp["terminated"])

    total, aux = world_model_loss(
        obs_log_probs,
        reward_lp,
        cont_lp,
        inp["posterior_logits"],
        inp["prior_logits"],
        continue_scale_factor=meta["continue_scale_factor"],
        **meta["kl_kwargs"],
    )

    expected = fixture["expected"]
    got = {
        "world_model_loss": float(total),
        "kl": float(aux["kl"]),
        "state_loss": float(aux["kl_loss"]),
        "reward_loss": float(aux["reward_loss"]),
        "observation_loss": float(aux["observation_loss"]),
        "continue_loss": float(aux["continue_loss"]),
    }
    for name, want in expected.items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"{name}: repo={got[name]!r} reference={want!r} — the jax math "
            "disagrees with the reference implementation on an identical batch"
        )


def test_ppo_losses_match_reference(fixture):
    from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss

    sec = fixture["ppo"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    clip = sec["clip_coef"]
    got = {
        "policy_loss": float(policy_loss(inp["new_logprobs"], inp["old_logprobs"], inp["advantages"], clip)),
        "value_loss_unclipped": float(
            value_loss(inp["new_values"], inp["old_values"], inp["returns"], clip, False)
        ),
        "value_loss_clipped": float(
            value_loss(inp["new_values"], inp["old_values"], inp["returns"], clip, True)
        ),
        # the reference IGNORES `reduction` in the clipped branch — ours must too
        "value_loss_clipped_sum_reduction": float(
            value_loss(inp["new_values"], inp["old_values"], inp["returns"], clip, True, "sum")
        ),
        "entropy_loss": float(entropy_loss(inp["entropy"])),
    }
    assert got.pop("value_loss_clipped_sum_reduction") == pytest.approx(
        sec["expected"]["value_loss_clipped"], rel=RTOL, abs=ATOL
    )
    for name, want in sec["expected"].items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"ppo {name}: repo={got[name]!r} reference={want!r}"
        )


def test_sac_losses_match_reference(fixture):
    from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss, critic_loss

    sec = fixture["sac"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    # reference layouts: qf_values (B, N), next_qf_value/logprobs/min_q (B, 1);
    # ours: qs (N, B), target/log_prob/min_q (B,)
    got = {
        "critic_loss": float(critic_loss(inp["qf_values"].T, inp["next_qf_value"][:, 0])),
        "policy_loss": float(actor_loss(sec["alpha"], inp["logprobs"][:, 0], inp["min_q"][:, 0])),
        "entropy_loss": float(
            alpha_loss(jnp.asarray(sec["log_alpha"]), inp["logprobs"][:, 0], sec["target_entropy"])
        ),
    }
    for name, want in sec["expected"].items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"sac {name}: repo={got[name]!r} reference={want!r}"
        )


def test_a2c_losses_match_reference(fixture):
    from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss

    sec = fixture["a2c"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    got = {
        "policy_loss_sum": float(policy_loss(inp["logprobs"], inp["advantages"], "sum")),
        "policy_loss_mean": float(policy_loss(inp["logprobs"], inp["advantages"], "mean")),
        "value_loss_sum": float(value_loss(inp["values"], inp["returns"], "sum")),
    }
    for name, want in sec["expected"].items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"a2c {name}: repo={got[name]!r} reference={want!r}"
        )


def test_dv1_losses_match_reference(fixture):
    from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
    from sheeprl_tpu.utils.distribution import Normal

    sec = fixture["dreamer_v1"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    obs_nll = -(
        Normal(inp["cnn_recon"], 1.0, event_dims=3).log_prob(inp["cnn_target"])
        + Normal(inp["mlp_recon"], 1.0, event_dims=1).log_prob(inp["mlp_target"])
    )
    reward_nll = -Normal(inp["reward_mean"], 1.0).log_prob(inp["rewards"])
    total, aux = reconstruction_loss(
        obs_nll, reward_nll, None,
        inp["post_mean"], inp["post_std"], inp["prior_mean"], inp["prior_std"],
        kl_free_nats=sec["kl_free_nats"], kl_regularizer=sec["kl_regularizer"],
    )
    got = {
        "reconstruction_loss": float(total),
        "kl": float(aux["kl"]),
        "state_loss": float(aux["kl_loss"]),
        "reward_loss": float(aux["reward_loss"]),
        "observation_loss": float(aux["observation_loss"]),
    }
    for name, want in sec["expected"].items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"dv1 {name}: repo={got[name]!r} reference={want!r}"
        )


def test_dv2_losses_match_reference(fixture):
    from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
    from sheeprl_tpu.utils.distribution import Bernoulli, Normal

    sec = fixture["dreamer_v2"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    obs_nll = -(
        Normal(inp["cnn_recon"], 1.0, event_dims=3).log_prob(inp["cnn_target"])
        + Normal(inp["mlp_recon"], 1.0, event_dims=1).log_prob(inp["mlp_target"])
    )
    reward_nll = -Normal(inp["reward_mean"], 1.0).log_prob(inp["rewards"])
    continue_nll = -sec["discount_scale_factor"] * Bernoulli(inp["continue_logits"]).log_prob(
        (1.0 - inp["terminated"]) * sec["gamma"]
    )
    total, aux = reconstruction_loss(
        obs_nll, reward_nll, continue_nll, inp["posterior_logits"], inp["prior_logits"],
        kl_balancing_alpha=sec["kl_balancing_alpha"],
        kl_free_nats=sec["kl_free_nats"], kl_regularizer=sec["kl_regularizer"],
    )
    got = {
        "reconstruction_loss": float(total),
        "kl": float(aux["kl"]),
        "kl_loss": float(aux["kl_loss"]),
        "reward_loss": float(aux["reward_loss"]),
        "observation_loss": float(aux["observation_loss"]),
        "continue_loss": float(aux["continue_loss"]),
    }
    for name, want in sec["expected"].items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"dv2 {name}: repo={got[name]!r} reference={want!r}"
        )


def test_p2e_intrinsic_reward_matches_reference(fixture):
    """The ensemble-disagreement intrinsic reward uses torch's UNBIASED
    variance in the reference — jnp.var needs ddof=1 to match (the
    mismatch is an N/(N-1) scale error on every intrinsic reward)."""
    from sheeprl_tpu.algos.p2e_utils import ensemble_disagreement

    sec = fixture["p2e"]
    preds = jnp.asarray(np.asarray(sec["inputs"]["preds"], np.float32))
    got = ensemble_disagreement(preds, sec["multiplier"])
    want = np.asarray(sec["expected"]["intrinsic_reward"], np.float32)
    np.testing.assert_allclose(np.asarray(got), want, rtol=RTOL, atol=ATOL)


def test_math_utils_match_reference(fixture):
    """GAE, TD(λ), the two-hot codec, and TF-style RMSprop against the
    reference implementations on identical seeded inputs.  Note the API
    difference under test: our two-hot codec applies symlog/symexp
    INTERNALLY (the reference composes them at call sites), so the
    comparison feeds symexp-ed inputs / wraps with symexp."""
    import optax

    from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values
    from sheeprl_tpu.utils.optim import rmsprop_tf
    from sheeprl_tpu.utils.utils import gae, symexp, two_hot_decoder, two_hot_encoder

    sec = fixture["math"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}

    returns, advantages = gae(
        inp["rewards"], inp["values"], inp["dones"], inp["next_value"][0],
        sec["gamma"], sec["gae_lambda"],
    )
    np.testing.assert_allclose(np.asarray(returns), sec["expected"]["returns"], rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(advantages), sec["expected"]["advantages"], rtol=RTOL, atol=ATOL)

    lam = compute_lambda_values(inp["lam_rewards"], inp["lam_values"], inp["lam_continues"], sec["lmbda"])
    np.testing.assert_allclose(np.asarray(lam), sec["expected"]["lambda_values"], rtol=RTOL, atol=ATOL)

    support, buckets = sec["two_hot_support"], sec["two_hot_buckets"]
    encoded = two_hot_encoder(symexp(inp["two_hot_x"]), support, buckets)
    np.testing.assert_allclose(
        np.asarray(encoded), sec["expected"]["two_hot_encoded"], rtol=1e-4, atol=1e-4
    )
    decoded = two_hot_decoder(inp["two_hot_probs"], support)
    np.testing.assert_allclose(
        np.asarray(decoded), symexp(jnp.asarray(sec["expected"]["two_hot_decoded"])), rtol=RTOL, atol=ATOL
    )

    r = sec["rmsprop"]
    opt = rmsprop_tf(r["lr"], decay=r["alpha"], eps=r["eps"], momentum=r["momentum"])
    p = inp["opt_param"]
    state = opt.init(p)
    for i in range(3):
        updates, state = opt.update(inp["opt_grads"][i], state, p)
        p = optax.apply_updates(p, updates)
    np.testing.assert_allclose(
        np.asarray(p), sec["expected"]["rmsprop_param_after_3_steps"], rtol=1e-4, atol=1e-5
    )


def test_ratio_matches_reference(fixture):
    """The Ratio replay governor follows the reference's (Hafner's) law:
    the first call converts pretrain_steps (clamped to the current steps)
    when set, else the current steps; later calls convert the step delta
    with the fractional remainder carried in step units."""
    import warnings

    from sheeprl_tpu.utils.utils import Ratio

    for case in fixture["math"]["ratio_cases"]:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = Ratio(case["ratio"], pretrain_steps=case["pretrain_steps"])
            got = [r(c) for c in case["calls"]]
        assert got == case["expected"], (
            f"ratio={case['ratio']} pretrain={case['pretrain_steps']} "
            f"calls={case['calls']}: repo={got} reference={case['expected']}"
        )
        # state roundtrip mid-stream preserves the future output stream
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r1 = Ratio(case["ratio"], pretrain_steps=case["pretrain_steps"])
            r1(case["calls"][0])
            r2 = Ratio(case["ratio"]).load_state_dict(r1.state_dict())
            for c in case["calls"][1:]:
                assert r1(c) == r2(c)


def test_truncated_normal_matches_reference(fixture):
    from sheeprl_tpu.utils.distribution import TruncatedNormal

    sec = fixture["truncated_normal"]
    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in sec["inputs"].items()}
    d = TruncatedNormal(inp["loc"], inp["scale"], -1.0, 1.0)
    np.testing.assert_allclose(
        np.asarray(d.log_prob(inp["value"])), sec["expected"]["log_prob"], rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(d.mean), sec["expected"]["mean"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(d.entropy()), sec["expected"]["entropy"], rtol=1e-4, atol=1e-5
    )


def test_sac_ae_decoder_target_matches_reference(fixture):
    """The 5-bit quantized decoder target (dither zeroed on both sides)
    against the reference preprocess_obs; the train step adds the dither
    from its own PRNG stream (sac_ae.py one_update)."""
    sec = fixture["sac_ae"]
    raw = jnp.asarray(np.asarray(sec["inputs"]["raw"], np.float32))
    got = jnp.floor(raw / 8.0) / 32.0 - 0.5  # the deterministic part of the target
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(sec["expected"]["target"], np.float32), rtol=RTOL, atol=ATOL
    )
