"""Cross-IMPLEMENTATION loss check (VERDICT r3 #4).

`reference_fixture.json` holds one seeded batch and the loss values the
REFERENCE implementation (torch, /root/reference/sheeprl/algos/dreamer_v3/
loss.py:9-88) computed for it — regenerate with make_reference_fixture.py.
Here the repo's jax implementation consumes the SAME batch and must land on
the SAME numbers in fp32.  Unlike the self-captured goldens
(test_golden.py), a pass here means the math agrees with an independent
implementation, not merely with yesterday's self.

Covers in one batch: MSE pixel reconstruction, symlog vector
reconstruction, two-hot symlog reward NLL, Bernoulli continue NLL, and the
free-nats-clipped balanced categorical KL.
"""

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

FIXTURE_PATH = Path(__file__).parent / "reference_fixture.json"

# fp32 accumulation-order slack between XLA and torch
RTOL = 2e-5
ATOL = 1e-6


@pytest.fixture(scope="module")
def fixture():
    assert FIXTURE_PATH.exists(), "run make_reference_fixture.py (needs /root/reference)"
    return json.loads(FIXTURE_PATH.read_text())


def test_world_model_losses_match_reference(fixture):
    from sheeprl_tpu.algos.dreamer_v3.loss import world_model_loss
    from sheeprl_tpu.utils.distribution import (
        Bernoulli,
        MSEDistribution,
        SymlogDistribution,
        TwoHotEncodingDistribution,
    )

    inp = {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in fixture["inputs"].items()}
    meta = fixture["meta"]

    obs_log_probs = {
        "rgb": MSEDistribution(inp["cnn_recon"], event_dims=len(meta["shapes"]["cnn"])).log_prob(
            inp["cnn_target"]
        ),
        "state": SymlogDistribution(inp["mlp_recon"], event_dims=1).log_prob(inp["mlp_target"]),
    }
    reward_lp = TwoHotEncodingDistribution(inp["reward_logits"], dims=1).log_prob(
        inp["rewards"][..., None]
    )
    cont_lp = Bernoulli(inp["continue_logits"], event_dims=0).log_prob(1.0 - inp["terminated"])

    total, aux = world_model_loss(
        obs_log_probs,
        reward_lp,
        cont_lp,
        inp["posterior_logits"],
        inp["prior_logits"],
        continue_scale_factor=meta["continue_scale_factor"],
        **meta["kl_kwargs"],
    )

    expected = fixture["expected"]
    got = {
        "world_model_loss": float(total),
        "kl": float(aux["kl"]),
        "state_loss": float(aux["kl_loss"]),
        "reward_loss": float(aux["reward_loss"]),
        "observation_loss": float(aux["observation_loss"]),
        "continue_loss": float(aux["continue_loss"]),
    }
    for name, want in expected.items():
        assert got[name] == pytest.approx(want, rel=RTOL, abs=ATOL), (
            f"{name}: repo={got[name]!r} reference={want!r} — the jax math "
            "disagrees with the reference implementation on an identical batch"
        )
