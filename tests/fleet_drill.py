#!/usr/bin/env python
"""run_ci stage 16: fault-tolerant serving-fleet drill.

A tiny committed PPO snapshot is served by a REAL 2-replica fleet
(``LocalFleet`` spawning ``python -m sheeprl_tpu.serve`` twice) behind a
``FleetRouter``/``FleetServer`` front, then attacked three ways at once:

1. **injected replica faults** — a seeded ``serve.replica`` raise plan
   fires on the router→replica leg every few forwards, so failover runs
   continuously, not just at the kill;
2. **replica murder** — one replica is SIGKILLed mid-stream; the
   supervisor respawns it, the router ejects/readmits it;
3. **poisoned rollout** — a newer checkpoint with a flipped shard byte is
   committed (the watcher's CRC verify must reject it before ANY replica
   is asked to reload), followed by a good commit that must roll out to
   every replica.

Gates: zero dropped requests, every session completes, the router's
stats/metrics show the failovers and the halted-then-completed rollout,
and both replicas end up serving the new step.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG_DIR = "/tmp/run_ci_fleet"
N_CLIENTS = 8
N_REQUESTS = 30

# fires in the ROUTER process only (the forward leg) — replicas inherit the
# env var but never call these sites
FAULT_PLAN = json.dumps(
    {"seed": 7, "plan": [{"site": "serve.replica", "kind": "raise", "every": 23}]}
)


def _train_tiny() -> str:
    from sheeprl_tpu.cli import run
    from tests.ckpt_utils import find_checkpoints

    run(
        [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "fabric.accelerator=cpu",
            "metric.log_level=0",
            "checkpoint.every=1",
            "buffer.memmap=False",
            "algo.learning_starts=0",
            f"log_dir={LOG_DIR}",
            "print_config=False",
            "algo.run_test=False",
        ]
    )
    ckpts = find_checkpoints(LOG_DIR)
    assert ckpts, f"dryrun produced no committed checkpoint under {LOG_DIR}"
    return str(ckpts[-1])


def main() -> int:
    shutil.rmtree(LOG_DIR, ignore_errors=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    ckpt = _train_tiny()
    # plant the chaos plan AFTER training (the trainer would otherwise trip
    # over plan validation for serve-only sites it never fires)
    os.environ["SHEEPRL_FAULT_PLAN"] = FAULT_PLAN
    from sheeprl_tpu.resilience.faults import install_from_env

    install_from_env()

    import numpy as np

    from sheeprl_tpu.checkpoint.protocol import (
        checkpoint_step,
        shard_name,
        step_dir_name,
        write_commit,
        write_shard,
    )
    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.fleet import FleetRouter, FleetServer, LocalFleet
    from sheeprl_tpu.serve.loader import checkpoint_root, resolve_checkpoint

    ckpt_path = resolve_checkpoint(ckpt)
    root = checkpoint_root(ckpt_path)
    base_step = checkpoint_step(ckpt_path)
    assert root is not None and base_step >= 0, (ckpt_path, base_step)

    cfg = {
        "serve": {
            "fleet": {
                "health_poll_s": 0.2,
                "eject_threshold": 2,
                "readmit_s": 0.5,
                "route_retries": 3,
                "request_timeout_s": 60.0,
                "drain_timeout_s": 10.0,
                "reload_poll_s": 3600.0,  # rollouts driven by hand below
            }
        }
    }
    fleet = LocalFleet(
        str(ckpt_path),
        overrides=["serve.batch_ladder=[1,8]", "serve.max_wait_ms=2"],
        replicas=2,
        backoff_base_s=0.2,
        backoff_max_s=1.0,
        echo=False,
    )
    fleet.start()
    server = None
    try:
        router = FleetRouter(fleet.addresses(), cfg, ckpt_root=root)
        fleet.attach(router)
        server = FleetServer(router)
        server.start()
        assert router.wait_healthy(min_replicas=2, timeout=120.0), router.health()
        print(f"[drill] fleet up: 2 replicas behind {server.url}")

        # -- phase 1: chaos load (injected faults + SIGKILL mid-stream) ------
        health = PolicyClient(server.url, timeout=120.0).health()
        obs = {
            k: np.zeros(shape, np.dtype(dt))
            for k, (shape, dt) in health["obs_spec"].items()
        }
        errors, done = [], []
        barrier = threading.Barrier(N_CLIENTS + 1)

        def client_thread(cid: int) -> None:
            client = PolicyClient(server.url, timeout=120.0, retries=6, retry_base_s=0.2)
            barrier.wait(timeout=120.0)
            try:
                for _ in range(N_REQUESTS):
                    client.act(obs, greedy=True, session=f"drill-{cid}")
                    time.sleep(0.05)
                done.append(cid)
            except Exception as e:  # noqa: BLE001 — the gate IS "no exception"
                errors.append((cid, repr(e)))

        threads = [
            threading.Thread(target=client_thread, args=(i,)) for i in range(N_CLIENTS)
        ]
        for t in threads:
            t.start()
        barrier.wait(timeout=120.0)
        time.sleep(0.4)
        fleet.kill(0, sig=signal.SIGKILL)
        print("[drill] replica r0 SIGKILLed mid-stream")
        for t in threads:
            t.join(300.0)
        assert not errors, f"dropped requests: {errors}"
        assert sorted(done) == list(range(N_CLIENTS)), "a session failed to complete"
        stats = router.stats()
        assert stats["routed"] >= N_CLIENTS * N_REQUESTS, stats
        assert stats["failovers"] >= 1, stats
        print(
            f"[drill] chaos load OK: {stats['routed']} routed, "
            f"{stats['failovers']} failovers, {stats['ejects']} ejects, 0 drops"
        )

        # the supervisor must bring slot r0 back before the rollout phase
        # (the rollout skips unprobed slots; the point is reloading BOTH)
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if sum(1 for r in router.replica_list() if r.routable) >= 2:
                break
            time.sleep(0.5)
        routable = sum(1 for r in router.replica_list() if r.routable)
        assert routable == 2, f"respawned replica never readmitted: {router.health()}"
        assert router.stats()["respawns"] >= 1, router.stats()
        print("[drill] respawn OK: killed replica is back and routable")

        # -- phase 2: poisoned rollout halts before any replica --------------
        state = {"agent": {"w": np.arange(32, dtype=np.float64)}}
        poison_step = base_step + 100
        poison_dir = root / step_dir_name(poison_step)
        poison_dir.mkdir()
        write_shard(poison_dir, 0, state)
        assert write_commit(poison_dir, poison_step, world=1, timeout_s=30.0)
        shard = poison_dir / shard_name(0)
        raw = bytearray(shard.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        shard.write_bytes(bytes(raw))

        code, payload = router.reload_once()
        assert code == 200 and payload["reloaded"] is False, payload
        assert router._fleet_store.step == base_step, router._fleet_store.step
        per_replica = router.health()["per_replica"]
        for rid, desc in per_replica.items():
            assert desc["checkpoint_step"] == base_step, (rid, desc)
        print(f"[drill] poison OK: step {poison_step} rejected, fleet still at {base_step}")

        # -- phase 3: a good commit rolls out to every replica ---------------
        good_step = base_step + 200
        good_dir = root / step_dir_name(good_step)
        good_dir.mkdir()
        # replicas reload a REAL snapshot: reuse the served checkpoint's
        # payload so the player rebuild succeeds
        import pickle

        with open(ckpt_path / shard_name(0), "rb") as f:
            good_state = pickle.load(f)
        write_shard(good_dir, 0, good_state)
        assert write_commit(good_dir, good_step, world=1, timeout_s=30.0)

        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline and router._fleet_store.step != good_step:
            router.reload_once()
            time.sleep(0.5)  # reload breaker cool-down after the poison
        assert router._fleet_store.step == good_step, (
            router._fleet_store.step,
            router.watcher.last_error,
        )
        for rid, desc in router.health()["per_replica"].items():
            assert desc["checkpoint_step"] == good_step, (rid, desc)
        stats = router.stats()
        assert stats["rolling_reloads"] >= 1, stats
        assert stats["reload_halts"] == 0, stats  # poison never reached a replica
        print(f"[drill] rolling reload OK: both replicas serve step {good_step}")

        # -- metrics surface --------------------------------------------------
        import urllib.request

        with urllib.request.urlopen(server.url + "/metrics", timeout=30) as resp:
            body = resp.read().decode()
        for needle in (
            "sheeprl_fleet_replicas 2.0",
            "sheeprl_fleet_failovers",
            "sheeprl_fleet_respawns",
            "sheeprl_fleet_rolling_reloads",
        ):
            assert needle in body, f"{needle!r} missing from /metrics"
        print(
            "fleet drill OK: injected faults + SIGKILL + poisoned commit -> "
            "0 drops, respawn readmitted, rollout halted on poison and "
            "completed on the good commit"
        )
        return 0
    finally:
        if server is not None:
            server.stop()
        fleet.stop()


if __name__ == "__main__":
    sys.exit(main())
