"""Pipeline parallelism (parallel/pipeline.py + the dreamer_v3 stage split).

The load-bearing claims, in dependency order:

1. the 1F1B schedule is a valid execution order (every unit once, deps
   respected, the per-stage in-flight memory bound holds);
2. gumbel-argmax sampling with hoisted noise is BIT-identical to
   ``jax.random.categorical`` — the sample-invariance law that lets the
   pipelined RSSM draw the exact posterior samples the monolithic baseline
   draws regardless of microbatching;
3. ``pipeline_value_and_grad`` equals monolithic ``jax.value_and_grad`` on
   a synthetic chain (pure reassociation, tight tolerance);
4. the ISSUE 16 acceptance cell: pipelined dreamer_v3 on a fake pipeline
   mesh matches the data-parallel baseline's losses/params within the
   DRIFT.md tiers, compile-once across ≥50 windows under the armed
   transfer guard;
5. an indivisible microbatch split errors with the shard_batch-style
   message (the divisibility law), not an opaque XLA reshape error.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel import pipeline as pl
from sheeprl_tpu.parallel.fabric import build_fabric
from sheeprl_tpu.utils.distribution import OneHotCategorical

# same XS footprint as tests/test_sharding/test_mesh_e2e.py: every sharded
# dim a multiple of 4 so 4-way axis products tile without demotions
TINY = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.id=discrete_dummy",
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=4",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=32",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "fabric.accelerator=cpu",
    "fabric.devices=8",
    "fabric.precision=32-true",
]


# --------------------------------------------------------------------------
# 1. schedule
# --------------------------------------------------------------------------

@pytest.mark.parametrize("stages", [1, 2, 3])
@pytest.mark.parametrize("microbatches", [3, 4, 6])
def test_one_f_one_b_is_a_valid_order(stages, microbatches):
    if microbatches < stages:
        pytest.skip("resolve_pipeline forbids M < S")
    order = pl.one_f_one_b(stages, microbatches)
    # every unit exactly once
    assert sorted(order) == sorted(
        [(op, s, m) for op in ("F", "B") for s in range(stages) for m in range(microbatches)]
    )
    pos = {unit: i for i, unit in enumerate(order)}
    live = [0] * stages
    peak = [0] * stages
    for op, s, m in order:
        if op == "F":
            if s > 0:
                assert pos[("F", s - 1, m)] < pos[("F", s, m)], "forward before its feeder"
            live[s] += 1
            peak[s] = max(peak[s], live[s])
        else:
            assert pos[("F", s, m)] < pos[("B", s, m)], "backward before its forward"
            if s < stages - 1:
                assert pos[("B", s + 1, m)] < pos[("B", s, m)], "backward before its cotangent"
            live[s] -= 1
    # the 1F1B liveness bound: at most S - s activations in flight at stage s
    for s in range(stages):
        assert peak[s] <= stages - s, (s, peak)
    if microbatches > stages > 1:
        # the defining 1F1B property (vs GPipe): the last stage starts
        # draining backwards before the first stage has injected everything
        assert pos[("B", stages - 1, 0)] < pos[("F", 0, microbatches - 1)]


def test_bubble_fraction():
    assert pl.bubble_fraction(1, 8) == 0.0
    assert pl.bubble_fraction(2, 4) == pytest.approx(1 / 5)
    assert pl.bubble_fraction(4, 12) == pytest.approx(3 / 15)


def test_resolve_pipeline_validates():
    assert not pl.resolve_pipeline({}).enabled
    with pytest.raises(ValueError, match="must be >= pipeline.stages"):
        pl.resolve_pipeline({"pipeline": {"stages": 4, "microbatches": 2}})
    with pytest.raises(ValueError, match="schedule"):
        pl.resolve_pipeline({"pipeline": {"stages": 2, "microbatches": 4, "schedule": "gpipe"}})
    spec = pl.resolve_pipeline({"pipeline": {"stages": 2, "microbatches": 4}})
    assert spec.enabled and spec.bubble_frac == pytest.approx(1 / 5)
    with pytest.raises(ValueError, match="implemented for"):
        spec.check_algo("dreamer_v1")
    spec.check_algo("dreamer_v3")  # no raise


# --------------------------------------------------------------------------
# 2. sample invariance
# --------------------------------------------------------------------------

def test_hoisted_noise_sampling_is_bit_identical():
    """The keystone: categorical(key, logits) == argmax(logits + gumbel) at
    logits shape/dtype, and row slices of the noise commute with argmax —
    so full-batch noise sliced per microbatch reproduces the baseline's
    samples EXACTLY."""
    key = jax.random.PRNGKey(7)
    logits = jax.random.normal(jax.random.PRNGKey(1), (16, 4, 8), jnp.float32)
    dist = OneHotCategorical(logits, unimix=0.01)
    baseline = dist.sample(key)
    noise = OneHotCategorical.sample_noise(key, dist.logits.shape, dist.logits.dtype)
    assert (dist.sample_from_noise(noise) == baseline).all()
    # microbatch slices: same rows, same bits
    for sl in (slice(0, 8), slice(8, 16)):
        mb = OneHotCategorical(logits[sl], unimix=0.01)
        assert (mb.sample_from_noise(noise[sl]) == baseline[sl]).all()
    # straight-through surface agrees too
    assert (dist.rsample_from_noise(noise) == dist.rsample(key)).all()


# --------------------------------------------------------------------------
# 3. microbatch plumbing + synthetic chain
# --------------------------------------------------------------------------

def test_split_merge_roundtrip_and_remainder_error():
    x = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)
    parts = pl.split_microbatches(x, 4, axis=1)
    assert parts.shape == (4, 2, 2, 3)
    # contiguous row chunks on the batch axis
    np.testing.assert_array_equal(np.asarray(parts[1]), np.asarray(x[:, 2:4]))
    np.testing.assert_array_equal(np.asarray(pl.merge_microbatches(parts, axis=1)), np.asarray(x))
    with pytest.raises(ValueError, match="cannot split axis 1 .*3 microbatches"):
        pl.split_microbatches(x, 3, axis=1)


def test_chunked_rows_exact_and_remainder_error():
    x = jnp.arange(12 * 3, dtype=jnp.float32).reshape(12, 3)
    fn = lambda r: jnp.tanh(r @ jnp.ones((3, 5)))  # noqa: E731
    np.testing.assert_array_equal(np.asarray(pl.chunked_rows(fn, x, 4)), np.asarray(fn(x)))
    assert pl.chunked_rows(fn, x, 1) is not None  # passthrough path
    with pytest.raises(ValueError, match="imagination batch of 12 rows"):
        pl.chunked_rows(fn, x, 5)


def test_pipeline_value_and_grad_matches_monolithic():
    """3-stage synthetic chain vs plain value_and_grad on the full batch:
    identical math up to reassociation of the microbatch mean."""
    kp = jax.random.PRNGKey(0)
    params = {
        "w0": jax.random.normal(jax.random.fold_in(kp, 0), (6, 8)),
        "w1": jax.random.normal(jax.random.fold_in(kp, 1), (8, 8)),
        "w2": jax.random.normal(jax.random.fold_in(kp, 2), (8, 4)),
    }
    data = jax.random.normal(jax.random.fold_in(kp, 3), (16, 6))
    target = jax.random.normal(jax.random.fold_in(kp, 4), (16, 4))

    def s0(p, _c, const):
        return jnp.tanh(const["x"] @ p["w0"])

    def s1(p, c, const):
        del const
        return jnp.tanh(c @ p["w1"])

    def s2(p, c, const):
        err = c @ p["w2"] - const["y"]
        return jnp.mean(err**2), {"mae": jnp.mean(jnp.abs(err))}

    def monolithic(p, x, y):
        loss, aux = s2(p, s1(p, s0(p, None, {"x": x}), None), {"y": y})
        return loss, aux

    (ref_loss, ref_aux), ref_grads = jax.value_and_grad(monolithic, has_aux=True)(
        params, data, target
    )
    consts = pl.split_microbatches({"x": data, "y": target}, 4, axis=0)
    loss, aux, grads = pl.pipeline_value_and_grad(
        (s0, s1, s2), params, consts, microbatches=4
    )
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(float(aux["mae"].mean()), float(ref_aux["mae"]), rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), rtol=1e-5, atol=1e-6
        )


def test_compose_pipeline_rules():
    from jax.sharding import PartitionSpec as P

    rules = (
        ("a", P(None, "model")),
        ("b", P("model", None)),
        ("c", None),
        ("d", lambda path, leaf, mesh: P(None, "model")),
    )
    both = dict(pl.compose_pipeline_rules(rules, has_model=True))
    assert both["a"] == P(None, ("pipeline", "model"))
    assert both["b"] == P(("pipeline", "model"), None)
    assert both["c"] is None
    assert both["d"]("p", None, None) == P(None, ("pipeline", "model"))
    pp_only = dict(pl.compose_pipeline_rules(rules, has_model=False))
    assert pp_only["a"] == P(None, "pipeline")


# --------------------------------------------------------------------------
# 4. the dreamer_v3 acceptance cell
# --------------------------------------------------------------------------

def _one_step(extra=(), repeats=1, windows=None):
    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    cfg = compose(list(TINY) + list(extra))
    fabric = build_fabric(cfg)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
        params=params, opt_state=opt_state,
    )
    rng = np.random.default_rng(0)
    U, L, B = 1, 8, 8
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    block = fabric.shard_batch(block, axis=2)
    params, opt_state, metrics = train_phase(
        params, opt_state, block, jax.random.PRNGKey(3), jnp.int32(0)
    )
    for i in range(1, repeats):
        params, opt_state, metrics = train_phase(
            params, opt_state, block, jax.random.PRNGKey(3), jnp.int32(i)
        )
    if windows:
        # ISSUE 16 acceptance: ≥N steady windows under the armed transfer
        # guard with ONE executable.  Keys/counter staged on device OUTSIDE
        # the guard; inside, only compiled dispatch + device-side arithmetic.
        from sheeprl_tpu.data.device_replay import steady_guard

        keys = [k for k in jax.random.split(jax.random.PRNGKey(9), windows)]
        counters = [jnp.int32(repeats + i) for i in range(windows)]
        jax.block_until_ready((params, opt_state))
        with steady_guard(True):
            for i in range(windows):
                params, opt_state, metrics = train_phase(
                    params, opt_state, block, keys[i], counters[i]
                )
    jax.block_until_ready(metrics)
    return fabric, train_phase, params, opt_state, jax.device_get(metrics)


PIPE_2STAGE = [
    "fabric.mesh_shape={data: 2, pipeline: 4}",
    "pipeline=2stage",  # stages: 2, microbatches: 4
    "pipeline.imagination_microbatches=2",
]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dv3_pipelined_matches_dp_baseline():
    """DP-vs-pipelined parity within the DRIFT.md tensor-parallel tiers
    (same cell shape as test_mesh_e2e's DP-vs-TP): the 2-stage 1F1B pipeline
    on a {data: 2, pipeline: 4} mesh trains the same XS model to the same
    losses/params as the pure-data 8-device baseline."""
    fab, train_phase, p_pp, _, m_pp = _one_step(PIPE_2STAGE, repeats=2)
    assert fab.pipeline_axis == "pipeline" and fab.model_axis is None
    assert dict(fab.mesh.shape) == {"data": 2, "pipeline": 4}

    # weights actually tiled over the pipeline axis (composed rule table)
    from sheeprl_tpu.parallel import sharding as shd

    flat, _ = shd.tree_paths_and_leaves(p_pp)
    specs = {p: l.sharding.spec for p, l in flat if isinstance(l, jax.Array)}
    gru = [s for p, s in specs.items() if "recurrent_model/gru/fused/kernel" in p]
    assert gru and any("pipeline" in str(s) for s in gru), gru

    # compile-once under the pipeline: repeats hit ONE executable
    assert train_phase.cache_size() == 1

    _, _, p_dp, _, m_dp = _one_step((), repeats=2)
    for a, b in zip(jax.tree_util.tree_leaves(m_pp), jax.tree_util.tree_leaves(m_dp)):
        b_arr = np.asarray(b)
        rtol = 1e-2 if np.all(np.abs(b_arr) > 10) else 1e-1
        np.testing.assert_allclose(np.asarray(a), b_arr, rtol=rtol, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_pp), jax.tree_util.tree_leaves(p_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-3)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dv3_pipelined_decoupled_rssm_matches_dp_baseline():
    """Same parity claim for the DecoupledRSSM branch (batched posterior
    sampling outside the scan — a different noise-consumption shape)."""
    dec = ["algo.world_model.decoupled_rssm=True"]
    _, _, p_pp, _, m_pp = _one_step(PIPE_2STAGE + dec)
    _, _, p_dp, _, m_dp = _one_step(dec)
    for a, b in zip(jax.tree_util.tree_leaves(m_pp), jax.tree_util.tree_leaves(m_dp)):
        b_arr = np.asarray(b)
        rtol = 1e-2 if np.all(np.abs(b_arr) > 10) else 1e-1
        np.testing.assert_allclose(np.asarray(a), b_arr, rtol=rtol, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_pp), jax.tree_util.tree_leaves(p_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-3)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dv3_pipelined_compile_once_50_guarded_windows():
    """cache_size()==1 across ≥50 update windows under the armed transfer
    guard — the compile-once law survives the trace-time-unrolled 1F1B
    schedule (ISSUE 16 acceptance)."""
    _, train_phase, *_ = _one_step(PIPE_2STAGE, windows=50)
    assert train_phase.cache_size() == 1


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dv3_microbatch_remainder_errors_clearly():
    """B=6 over microbatches=4: the divisibility law fires with the leaf
    spelled out (mirrors fabric.shard_batch), not an XLA reshape error."""
    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    cfg = compose(list(TINY) + PIPE_2STAGE)
    fabric = build_fabric(cfg)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
        params=params, opt_state=opt_state,
    )
    U, L, B = 1, 8, 6
    rng = np.random.default_rng(0)
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.zeros((U, L, B, 4), jnp.float32),
        "rewards": jnp.zeros((U, L, B), jnp.float32),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    block = fabric.shard_batch(block, axis=2)
    with pytest.raises(ValueError, match="multiples of pipeline.microbatches"):
        train_phase(params, opt_state, block, jax.random.PRNGKey(0), jnp.int32(0))


def test_pipeline_rejects_unsupported_algo():
    cfg = compose(list(TINY) + ["pipeline.stages=2", "pipeline.microbatches=4"])
    spec = pl.resolve_pipeline(cfg)
    with pytest.raises(ValueError, match="dreamer_v3"):
        spec.check_algo("p2e_dv3")


# --------------------------------------------------------------------------
# 5. the ≥5B XXL dryrun (abstract: params are eval_shape'd, not materialized)
# --------------------------------------------------------------------------

_XXL_DRYRUN = r"""
import jax, numpy as np, jax.numpy as jnp
from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel import sharding as shd
from sheeprl_tpu.parallel.fabric import build_fabric
from sheeprl_tpu.algos.dreamer_v3.agent import WorldModel

cfg = compose([
    "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy", "algo=dreamer_v3_XXL",
    "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]",
    "fabric.accelerator=cpu", "fabric.devices=32",
    "fabric.mesh_shape={data: 2, pipeline: 4, model: 4}",
    "pipeline=2stage",
    "sharding.undivisible=error",  # every sharded dim must tile: demotion = bug
])
fabric = build_fabric(cfg)
assert fabric.pipeline_axis == "pipeline" and fabric.model_axis == "model"
wm_cfg = cfg.algo.world_model
wm = WorldModel(
    cnn_keys=("rgb",), mlp_keys=(), cnn_shapes={"rgb": (64, 64, 3)}, mlp_shapes={},
    actions_dim=(4,), cnn_mult=wm_cfg.encoder.cnn_channels_multiplier,
    dense_units=cfg.algo.dense_units, mlp_layers=cfg.algo.mlp_layers,
    recurrent_size=wm_cfg.recurrent_model.recurrent_state_size,
    hidden_size=wm_cfg.transition_model.hidden_size,
    repr_hidden_size=wm_cfg.representation_model.hidden_size,
    stochastic_size=wm_cfg.stochastic_size, discrete_size=wm_cfg.discrete_size,
    unimix=cfg.algo.unimix, bins=wm_cfg.reward_model.bins,
    learnable_initial_state=wm_cfg.learnable_initial_recurrent_state,
    decoupled_rssm=wm_cfg.decoupled_rssm, use_pallas_gru=False,
    fused_pallas_rssm=False, dtype=jnp.float32,
)
stoch = wm_cfg.stochastic_size * wm_cfg.discrete_size
rec = wm_cfg.recurrent_model.recurrent_state_size
shapes = jax.eval_shape(
    wm.init, jax.random.PRNGKey(0), {"rgb": jnp.zeros((1, 64, 64, 3), jnp.float32)},
    jnp.zeros((1, rec)), jnp.zeros((1, stoch)), jnp.zeros((1, 4)),
    jnp.ones((1, 1)), jax.random.PRNGKey(1),
)
n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
assert n >= 5_000_000_000, f"XXL world model is {n/1e9:.2f}B params, expected >=5B"
# undivisible=error: every matched spec tiles the 4x8 mesh cleanly, and the
# dominant kernels tile over the (pipeline, model) product
specs = shd.partition_specs(fabric.sharding_rules, shapes, fabric.mesh, undivisible="error")
flat, _ = shd.tree_paths_and_leaves(specs)
gru = [s for p, s in flat if "recurrent_model/gru/fused/kernel" in p]
assert gru and "pipeline" in str(gru[0]) and "model" in str(gru[0]), gru
print(f"XXL_OK {n}")
"""


@pytest.mark.slow
def test_dv3_xxl_5b_dryrun_4x8_mesh():
    """ISSUE 16 acceptance: the ≥5B XXL preset dryruns on a fake 4x8 mesh —
    param count and (pipeline, model) tiling verified ABSTRACTLY (6.1B fp32
    would need ~24 GiB just for params).  Subprocess: the 32-device XLA
    host-platform flag must be set before jax initializes."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
    out = subprocess.run(
        [sys.executable, "-c", _XXL_DRYRUN],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "XXL_OK" in out.stdout
