import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.fabric import Fabric, Precision, get_single_device_fabric


def test_precision_policies():
    p = Precision.from_string("bf16-mixed")
    assert p.param_dtype == jnp.float32 and p.compute_dtype == jnp.bfloat16
    assert Precision.from_string("32-true").compute_dtype == jnp.float32
    assert Precision.from_string("bf16-true").param_dtype == jnp.bfloat16
    with pytest.raises(ValueError):
        Precision.from_string("fp16-mixed")


def test_mesh_and_sharding():
    fab = Fabric(devices=8, accelerator="cpu")
    assert fab.world_size == 8
    x = fab.shard_batch(np.zeros((16, 4), np.float32))
    assert "data" in str(x.sharding.spec)
    y = fab.replicate(np.zeros((3,)))
    assert y.sharding.is_fully_replicated


def test_mesh_shape_extra_axes():
    # {data: -1, model: 2} → 4x2 mesh; model-axis sharding available
    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": -1, "model": 2})
    assert dict(fab.mesh.shape) == {"data": 4, "model": 2}
    w = jax.device_put(np.zeros((8, 6), np.float32), fab.sharding(None, "model"))
    assert w.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    # a matmul with model-sharded weights executes under jit
    x = fab.shard_batch(np.ones((8, 8), np.float32))
    out = jax.jit(lambda a, b: a @ b)(x, w)
    assert out.shape == (8, 6)


def test_too_many_devices_raises():
    with pytest.raises(ValueError):
        Fabric(devices=64, accelerator="cpu")


def test_single_device_fabric():
    fab = Fabric(devices=8, accelerator="cpu")
    single = get_single_device_fabric(fab)
    assert single.world_size == 1
    assert single.device == fab.device


def test_to_host_never_aliases():
    fab = Fabric(devices=1, accelerator="cpu")
    x = fab.replicate(jnp.ones((4,)))
    host_copy = fab.to_host(x)
    assert host_copy.unsafe_buffer_pointer() != x.unsafe_buffer_pointer()


def test_local_world_size_single_process():
    fab = Fabric(devices=4, accelerator="cpu")
    # single-process: every mesh device is local
    assert fab.local_world_size == fab.world_size == 4


def test_shard_batch_multihost_path(monkeypatch):
    # Force the process_count()>1 branch: host_local_array_to_global_array is
    # the sanctioned multi-host assembly path and must produce the same
    # mesh-sharded result as device_put does single-process.
    fab = Fabric(devices=4, accelerator="cpu")
    monkeypatch.setattr(Fabric, "num_processes", property(lambda self: 2))
    x = fab.shard_batch(np.arange(32, dtype=np.float32).reshape(8, 4))
    assert "data" in str(x.sharding.spec)
    np.testing.assert_array_equal(np.asarray(x).reshape(8, 4)[:, 0], np.arange(0, 32, 4))


def test_player_sync_deferred_semantics():
    from sheeprl_tpu.parallel.fabric import PlayerSync
    from sheeprl_tpu.utils.structured import dotdict

    fab = Fabric(devices=1, accelerator="cpu")
    cfg = dotdict({"algo": {"player": {"deferred_sync": True, "sync_every": 1, "device": "host"}}})
    psync = PlayerSync(fab, cfg, extract=lambda p: p["actor"])
    p0 = {"actor": jnp.zeros(2)}
    player = psync.init(p0)
    assert psync.staleness == 0
    # dispatch window 1: deferred -> player unchanged, refresh pending
    p1 = {"actor": jnp.ones(2)}
    player = psync.after_dispatch(p1, player_params=player)
    assert float(np.asarray(player)[0]) == 0.0
    # the player now acts on init weights while window-1 weights are
    # pending: one window of (visible) staleness
    assert psync.staleness == 1
    # window 2 start: the pending params land
    player = psync.before_dispatch(player)
    assert float(np.asarray(player)[0]) == 1.0
    assert psync.staleness == 0
    assert psync.metrics()["Player/param_staleness_max"] == 1.0
    # nothing pending: no-op
    assert psync.before_dispatch(player) is player


def test_player_sync_immediate_and_cadence():
    from sheeprl_tpu.parallel.fabric import PlayerSync
    from sheeprl_tpu.utils.structured import dotdict

    fab = Fabric(devices=1, accelerator="cpu")
    cfg = dotdict({"algo": {"player": {"deferred_sync": False, "sync_every": 2, "device": "host"}}})
    psync = PlayerSync(fab, cfg, extract=lambda p: p["actor"])
    player = psync.init({"actor": jnp.zeros(2)})
    # first completed training window: off-cadence (1 % 2), skipped entirely
    player = psync.after_dispatch({"actor": jnp.ones(2)}, player_params=player)
    assert float(np.asarray(player)[0]) == 0.0
    assert psync.staleness == 1
    # second window: on-cadence, immediate copy
    player = psync.after_dispatch({"actor": jnp.ones(2)}, player_params=player)
    assert float(np.asarray(player)[0]) == 1.0
    assert psync.staleness == 0
    # the immediate-sync staleness bound is sync_every (the off-cadence
    # window before each refresh) — the metric proves it never exceeded it
    assert psync.staleness_max <= psync.sync_every


def test_player_sync_cadence_counts_training_windows_not_updates():
    """The cadence gate must key on COMPLETED TRAINING WINDOWS: with a
    fractional replay_ratio the env-loop update counter fires training on a
    fixed parity, and an update-based gate could miss every training update
    (player stuck on init weights — r2 review finding)."""
    from sheeprl_tpu.parallel.fabric import PlayerSync
    from sheeprl_tpu.utils.structured import dotdict

    fab = Fabric(devices=1, accelerator="cpu")
    cfg = dotdict({"algo": {"player": {"deferred_sync": False, "sync_every": 2, "device": "host"}}})
    psync = PlayerSync(fab, cfg, extract=lambda p: p)
    player = psync.init(jnp.zeros(2))
    # training fires on odd env updates only (replay_ratio 0.5): the sync
    # must still happen on every 2nd *training* window
    synced = 0
    for window in range(1, 7):
        player = psync.after_dispatch(jnp.full(2, float(window)), player_params=player)
        if float(np.asarray(player)[0]) == float(window):
            synced += 1
    assert synced == 3  # windows 2, 4, 6


def test_player_sync_staleness_bound_deferred_cadence():
    """ISSUE 12 satellite: the deferred-sync staleness is now observable
    and must respect its bound — at most ``sync_every`` windows behind
    (the pending refresh lands one ``before_dispatch`` later) over a long
    window stream, with the running max reported as a metric."""
    from sheeprl_tpu.parallel.fabric import PlayerSync
    from sheeprl_tpu.utils.structured import dotdict

    fab = Fabric(devices=1, accelerator="cpu")
    sync_every = 3
    cfg = dotdict({"algo": {"player": {"deferred_sync": True, "sync_every": sync_every, "device": "host"}}})
    psync = PlayerSync(fab, cfg, extract=lambda p: p)
    player = psync.init(jnp.zeros(2))
    for window in range(1, 20):
        player = psync.before_dispatch(player)
        assert psync.staleness <= sync_every, (window, psync.staleness)
        player = psync.after_dispatch(jnp.full(2, float(window)), player_params=player)
        assert psync.staleness <= sync_every, (window, psync.staleness)
    m = psync.metrics()
    assert m["Player/param_staleness_max"] <= sync_every
    # the bound is tight: the cadence really does let the player lag
    assert m["Player/param_staleness_max"] >= sync_every - 1


def test_player_device_selection():
    from unittest import mock

    from sheeprl_tpu.utils.structured import dotdict

    fab = Fabric(devices=1, accelerator="cpu")
    # on a CPU fabric host_device == device, so a wrong branch would be
    # invisible; pin host_device to a sentinel to assert the branch taken
    sentinel = object()
    with mock.patch.object(type(fab), "host_device", new_callable=mock.PropertyMock, return_value=sentinel):
        assert fab.player_device(dotdict({"algo": {}})) is sentinel
        assert (
            fab.player_device(dotdict({"algo": {"player": {"device": "accelerator"}}}))
            is fab.device
        )
    with pytest.raises(ValueError):
        fab.player_device(dotdict({"algo": {"player": {"device": "gpu"}}}))


def test_host_collectives_single_process():
    fab = Fabric(devices=2, accelerator="cpu")
    assert fab.broadcast_object({"a": 1}) == {"a": 1}
    assert fab.all_gather_object("x") == ["x"]
    fab.barrier()  # no-op single process


def test_seed_everything_rank_offsets_host_rng_only():
    """Host RNG (replay sampling, random prefill) must differ per rank, while
    the returned jax key (agent init + train-dispatch stream) must be
    IDENTICAL on every process — replicated global-program inputs have to
    agree across ranks (r2 review finding: rank-identical seeding made
    multi-host DP collect the same data num_processes times)."""
    from unittest import mock

    fab = Fabric(devices=1, accelerator="cpu")
    draws, keys = [], []
    for rank in (0, 1):
        with mock.patch("jax.process_index", return_value=rank):
            keys.append(np.asarray(fab.seed_everything(42)))
            draws.append(np.random.random(4))
    assert np.array_equal(keys[0], keys[1])  # shared jax stream
    assert not np.allclose(draws[0], draws[1])  # per-rank host RNG


def test_env_sharding_plan():
    fab = Fabric(devices=2, accelerator="cpu")
    sharded, global_envs = fab.env_sharding_plan(4, "PPO")
    assert sharded and global_envs == 4  # single-process: no inflation
    sharded, global_envs = fab.env_sharding_plan(3, "PPO")
    assert not sharded and global_envs == 3  # falls back to replication
    # multi-host: indivisible env counts must fail fast, BEFORE any rollout
    from unittest import mock

    with mock.patch("jax.process_count", return_value=2):
        with pytest.raises(ValueError, match="divisible"):
            fab.env_sharding_plan(3, "PPO")


def test_compilation_cache_dir_config(tmp_path):
    """fabric.compilation_cache_dir wires the persistent XLA compilation
    cache; entries appear for newly compiled programs."""
    import glob

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric

    cfg = compose(
        [
            "env=dummy", "env.id=discrete_dummy", "algo=ppo",
            "algo.total_steps=1", "algo.per_rank_batch_size=1",
            f"fabric.compilation_cache_dir={tmp_path}", "fabric.accelerator=cpu",
        ]
    )
    orig_dir = jax.config.jax_compilation_cache_dir
    orig_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        build_fabric(cfg)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.jit(lambda x: (x @ x.T).sum() + 41)(jnp.ones((64, 64))).block_until_ready()
        assert glob.glob(str(tmp_path) + "/*"), "no cache entries written"
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", orig_min)
        jax.config.update("jax_compilation_cache_dir", orig_dir)


def test_packed_copy_bit_identical():
    """_packed_copy (the single-transfer cross-platform player pull) must
    return the same values/shapes/dtypes as per-leaf device_put."""
    import numpy as np
    from sheeprl_tpu.parallel.fabric import _packed_copy

    rng = np.random.default_rng(0)
    leaves = [
        jnp.asarray(rng.normal(size=(3, 4)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(7,)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 255, (2, 2, 3)).astype(np.uint8)),
        jnp.asarray(rng.normal(size=()).astype(np.float32)),
        jnp.asarray(np.zeros((0, 5), np.float32)),  # empty leaf
    ]
    dev = jax.devices()[0]
    got = _packed_copy(leaves, dev)
    assert len(got) == len(leaves)
    for g, want in zip(got, leaves):
        assert g.dtype == want.dtype and g.shape == want.shape
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want))
        assert set(g.devices()) == {dev}


def test_packed_copy_preserves_weak_type():
    from sheeprl_tpu.parallel.fabric import _packed_copy

    leaves = [
        jnp.asarray([1.0, 2.0]),          # strong f32
        jnp.asarray([3.0]),               # strong f32
        jnp.array(0.5),                   # WEAK f32 scalar (log-alpha style)
    ]
    assert leaves[2].weak_type
    got = _packed_copy(leaves, jax.devices()[0])
    assert got[2].weak_type, "packed copy must not strip weak_type"
    assert not got[0].weak_type


def test_copy_to_survives_source_donation_on_same_platform_mesh():
    """The player-refresh pull must be a REAL copy even when the mesh and
    the player device share a platform: jax.device_put of a replicated
    multi-device array onto one of its own devices can be a zero-copy
    alias (jax 0.4.37 CPU), and the train step DONATES the source params —
    an aliased player copy would die mid-rollout with 'buffer has been
    deleted or donated'.  (Cross-platform TPU→host pulls always
    materialize, which is why real-chip runs never saw this.)"""
    from sheeprl_tpu.parallel.fabric import Fabric

    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4})
    params = fab.shard_params(
        {"kernel": jnp.ones((16, 8)), "bias": jnp.arange(4.0)}
    )
    host_copy = fab.copy_to(params, fab.host_device)
    jax.block_until_ready(host_copy)
    for leaf in jax.tree.leaves(params):
        leaf.delete()  # what donation does to the source tree
    for leaf in jax.tree.leaves(host_copy):
        np.asarray(leaf)  # must still be readable
    np.testing.assert_array_equal(np.asarray(host_copy["bias"]), np.arange(4.0))
