"""Sebulba device-group topology (parallel/topology.py): split validation,
topology resolution, and the ParamBroadcast staleness contract."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.parallel.topology import (
    DeviceTopology,
    ParamBroadcast,
    StalenessExceeded,
    resolve_topology,
)
from sheeprl_tpu.utils.structured import dotdict


def _cfg(**topology):
    return dotdict({"topology": topology})


class TestDeviceSplit:
    def test_default_split_one_actor_rest_learners(self):
        fab = Fabric(devices=4, accelerator="cpu")
        topo = DeviceTopology.from_config(fab, _cfg(actor_devices=1))
        assert topo.num_actors == 1 and topo.num_learners == 3
        assert set(topo.actor_devices).isdisjoint(topo.learner_devices)
        assert topo.learner_fabric.world_size == 3

    def test_explicit_two_two_split(self):
        fab = Fabric(devices=4, accelerator="cpu")
        topo = DeviceTopology.from_config(fab, _cfg(actor_devices=2, learner_devices=2))
        assert topo.num_actors == 2 and topo.num_learners == 2
        # the learner sub-mesh is a 1-D data mesh over exactly its group
        assert list(topo.learner_fabric.mesh.devices.flat) == topo.learner_devices

    def test_actor_group_swallowing_mesh_rejected(self):
        fab = Fabric(devices=4, accelerator="cpu")
        with pytest.raises(ValueError, match="no learner devices"):
            DeviceTopology.from_config(fab, _cfg(actor_devices=4))

    def test_oversubscribed_split_rejected(self):
        fab = Fabric(devices=4, accelerator="cpu")
        with pytest.raises(ValueError, match="exceeds"):
            DeviceTopology.from_config(fab, _cfg(actor_devices=2, learner_devices=3))

    def test_unassigned_devices_warn(self):
        fab = Fabric(devices=4, accelerator="cpu")
        with pytest.warns(RuntimeWarning, match="neither group"):
            topo = DeviceTopology.from_config(fab, _cfg(actor_devices=1, learner_devices=2))
        assert topo.num_actors + topo.num_learners == 3

    def test_single_device_degenerates_to_shared(self):
        fab = Fabric(devices=1, accelerator="cpu")
        with pytest.warns(RuntimeWarning, match="share the device"):
            topo = DeviceTopology.from_config(fab, _cfg(actor_devices=1))
        assert topo.shared and topo.actor_devices == topo.learner_devices


class TestResolution:
    def test_auto_without_sizing_stays_pipelined(self):
        fab = Fabric(devices=2, accelerator="cpu")
        assert resolve_topology(_cfg(name="auto"), fab) == "pipelined"
        assert resolve_topology(dotdict({}), fab) == "pipelined"

    def test_auto_with_sizing_upgrades(self):
        fab = Fabric(devices=2, accelerator="cpu")
        assert resolve_topology(_cfg(name="auto", actor_devices=1), fab) == "sebulba"

    def test_pipelined_pin_wins_over_sizing(self):
        fab = Fabric(devices=2, accelerator="cpu")
        assert resolve_topology(_cfg(name="pipelined", actor_devices=1), fab) == "pipelined"

    def test_sebulba_forced(self):
        fab = Fabric(devices=2, accelerator="cpu")
        assert resolve_topology(_cfg(name="sebulba"), fab) == "sebulba"

    def test_sebulba_rejects_model_axis(self):
        fab = Fabric(devices=4, accelerator="cpu", mesh_shape={"data": 2, "model": 2})
        with pytest.raises(ValueError, match="model"):
            resolve_topology(_cfg(name="sebulba"), fab)


class TestParamBroadcast:
    def _bcast(self, fab, **kw):
        return ParamBroadcast(fab, [fab.devices[0]], **kw)

    def test_publish_fetch_versions_and_d2d_copy(self):
        fab = Fabric(devices=2, accelerator="cpu")
        bc = ParamBroadcast(fab, [fab.devices[0]], max_staleness=2)
        params = fab.replicate({"w": jnp.arange(4.0)})
        v = bc.publish(params, version=0)
        assert v == 0
        got, version = bc.fetch(0)
        assert version == 0
        np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4.0))
        # the actor copy is committed to the actor device, not aliased to
        # the learner replica (the train step donates the learner buffers)
        assert set(got["w"].devices()) == {fab.devices[0]}
        bc.publish(params)  # auto-increment
        assert bc.version == 1
        assert bc.staleness(0) == 1

    def test_gate_blocks_until_fetch_within_bound(self):
        fab = Fabric(devices=2, accelerator="cpu")
        bc = self._bcast(fab, max_staleness=1, gate_timeout_s=30.0)
        params = fab.replicate({"w": jnp.zeros(2)})
        bc.publish(params, version=1)
        bc.publish(params, version=2)
        bc.publish(params, version=3)  # actor last fetched 0 → 3 behind

        fetched_at = {}

        def late_fetch():
            time.sleep(0.3)
            bc.fetch(0)
            fetched_at["t"] = time.monotonic()

        t = threading.Thread(target=late_fetch)
        t.start()
        waited = bc.gate()
        t.join()
        assert waited >= 0.2  # the learner really blocked on the actor
        assert bc.staleness(0) == 0

    def test_gate_times_out_loudly_on_wedged_actor(self):
        fab = Fabric(devices=2, accelerator="cpu")
        bc = self._bcast(fab, max_staleness=0, gate_timeout_s=0.2)
        params = fab.replicate({"w": jnp.zeros(2)})
        bc.publish(params, version=0)  # baseline (seeds the fetch cursors)
        bc.publish(params, version=1)  # the actor never picks this one up
        with pytest.raises(StalenessExceeded):
            bc.gate()

    def test_staleness_metrics_reported(self):
        fab = Fabric(devices=2, accelerator="cpu")
        bc = self._bcast(fab, max_staleness=4)
        params = fab.replicate({"w": jnp.zeros(2)})
        for v in range(0, 4):
            bc.publish(params, version=v)
        bc.fetch(0)
        m = bc.metrics()
        assert m["Sebulba/param_version"] == 3.0
        # the baseline publish (v0) seeds the cursors, so the observed lag
        # is the three updates the actor skipped — NOT the absolute version
        # (a resumed run publishing v999 first must not report 999)
        assert m["Sebulba/param_staleness_max"] == 3.0

    def test_resume_baseline_does_not_inflate_staleness(self):
        fab = Fabric(devices=2, accelerator="cpu")
        bc = self._bcast(fab, max_staleness=4)
        params = fab.replicate({"w": jnp.zeros(2)})
        bc.publish(params, version=999)  # resumed run's first publish
        _, v = bc.fetch(0)
        assert v == 999
        assert bc.metrics()["Sebulba/param_staleness_max"] == 0.0
