"""Full 2-process TRAINING smoke over ``jax.distributed`` (CPU backend).

Round-1 VERDICT weak #6 / STATUS r2 gap: the host collectives were tested
2-process, but no actual training loop had ever run with
``jax.process_count() > 1`` — log-dir broadcast, per-process env sampling,
``host_local_array_to_global_array`` batch assembly, and per-rank
checkpointing all short-circuit single-process.  Here two real processes
run the PPO CLI end-to-end against each other on a 2-device global mesh
(1 local CPU device per process) — the same control flow a 2-host TPU pod
slice executes over DCN.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_ALGO_ARGS = {
    "ppo": [
        "exp=ppo",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
    ],
    "sac": [
        "exp=sac",
        "env.id=continuous_dummy",
        "algo.learning_starts=0",
        "algo.hidden_size=16",
    ],
    # global-pool minibatching across processes (reference ppo.py:363-370)
    "ppo_share_data": [
        "exp=ppo",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=2",
        "buffer.share_data=True",
    ],
    # dedicated cross-process player/trainer split: process 0 = envs-only
    # player, process 1 = trainer sub-mesh (reference decoupled topology,
    # sheeprl/algos/ppo/ppo_decoupled.py:623-670)
    "ppo_decoupled_dedicated": [
        "exp=ppo_decoupled",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
        "algo.player.dedicated=True",
    ],
    # pixel obs exercise the (T,B,H,W,C) rollout layout on the trainer side
    # (obs_to_np rollout=True branch) — vector obs alone would miss it
    "ppo_decoupled_dedicated_pixels": [
        "exp=ppo_decoupled",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
        "algo.player.dedicated=True",
        "algo.cnn_keys.encoder=[rgb]",
        "env.screen_size=32",
    ],
    "sac_decoupled_dedicated": [
        "exp=sac_decoupled",
        "env.id=continuous_dummy",
        "algo.learning_starts=0",
        "algo.hidden_size=16",
        "algo.player.dedicated=True",
        "algo.player.sync_every=1",
        "buffer.checkpoint=True",
    ],
    # vector-obs DreamerV3 (no CNN): exercises the sequential-replay block
    # assembly + per-rank sampling + PlayerSync paths multi-process
    "dreamer_v3": [
        "exp=dreamer_v3",
        "env.id=discrete_dummy",
        "algo=dreamer_v3_XS",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_sequence_length=8",
        "algo.horizon=4",
        "algo.cnn_keys.encoder=[]",
        "algo.dense_units=16",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=16",
        "algo.world_model.transition_model.hidden_size=16",
        "algo.world_model.representation_model.hidden_size=16",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "buffer.size=400",
    ],
}

_WORKER = textwrap.dedent(
    """
    import glob, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    # cross-process CPU computations need explicit collectives (default
    # "none" raises "Multiprocess computations aren't implemented on the
    # CPU backend" from the first broadcast)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=int(os.environ.get("SMOKE_NPROC", "2")),
        process_id=int(sys.argv[1]),
    )
    from sheeprl_tpu.cli import run

    log_dir = os.environ["SMOKE_LOG_DIR"]
    run([
        *os.environ["SMOKE_ALGO_ARGS"].split(";"),
        "env=dummy",
        "dry_run=True",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        f"fabric.devices={os.environ.get('SMOKE_NPROC', '2')}",
        "fabric.accelerator=cpu",
        f"algo.per_rank_batch_size={os.environ.get('SMOKE_BATCH', '4')}",
        "algo.mlp_keys.encoder=[state]",
        "env.max_episode_steps=8",
        "algo.run_test=False",
        "metric.log_level=1",
        "metric.log_every=1",
        "checkpoint.every=1",
        "buffer.memmap=False",
        f"log_dir={log_dir}",
        "print_config=False",
    ])
    rank = jax.process_index()
    if rank == 0:
        from sheeprl_tpu.checkpoint import list_checkpoints

        ckpts = [
            c
            for root in glob.glob(f"{log_dir}/**/checkpoint", recursive=True)
            for c in list_checkpoints(root)
        ]
        assert ckpts, "rank 0 committed no checkpoint"
    print(f"rank {rank} TRAIN OK")
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize(
    "algo",
    [
        "ppo",
        "ppo_share_data",
        "sac",
        "dreamer_v3",
        "ppo_decoupled_dedicated",
        "ppo_decoupled_dedicated_pixels",
        "sac_decoupled_dedicated",
    ],
)
def test_two_process_training(tmp_path, algo):
    _run_distributed(tmp_path, _ALGO_ARGS[algo], nproc=2)


def _run_distributed(tmp_path, algo_args, nproc=2, batch=4, subdir="logs", timeout=420):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    log_dir = str(tmp_path / subdir)
    env = {
        **os.environ,
        "COORD": f"127.0.0.1:{port}",
        "SMOKE_ALGO_ARGS": ";".join(algo_args),
        "SMOKE_LOG_DIR": log_dir,
        "SMOKE_NPROC": str(nproc),
        "SMOKE_BATCH": str(batch),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out}"
        assert f"rank {i} TRAIN OK" in out
    return log_dir


def _final_agent_params(log_dir):
    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from tests.ckpt_utils import find_checkpoints

    ckpts = find_checkpoints(log_dir)
    assert ckpts, f"no checkpoint under {log_dir}"
    return load_checkpoint(ckpts[-1])["agent"]


@pytest.mark.slow
def test_dedicated_three_process_two_trainers(tmp_path):
    """1 player + 2 trainers (VERDICT r2 #5): the lockstep rollout/weight
    broadcast protocol has to survive a trainer SUB-MESH of size 2, and the
    result must be seed-identical to the 1-trainer topology — the global
    batch is the same; only its sharding over trainers differs (GSPMD
    all-reduce ⇒ same update)."""
    import jax
    import numpy as np

    args = [
        "exp=ppo_decoupled",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
        "algo.player.dedicated=True",
    ]
    # same GLOBAL minibatch (4): 1 trainer × 4/rank  vs  2 trainers × 2/rank
    dir_1t = _run_distributed(tmp_path, args, nproc=2, batch=4, subdir="logs_1t")
    dir_2t = _run_distributed(tmp_path, args, nproc=3, batch=2, subdir="logs_2t")
    p1 = _final_agent_params(dir_1t)
    p2 = _final_agent_params(dir_2t)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat2 = jax.tree_util.tree_leaves(p2)
    assert len(flat1) == len(flat2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_dedicated_five_process_four_trainers(tmp_path):
    """1 player + 4 trainers (VERDICT r4 #9): trainer-count invariance must
    hold beyond the 2-trainer sub-mesh — same global minibatch (4) split as
    1×4 vs 4×1 must yield IDENTICAL final params (GSPMD all-reduce over a
    4-way data axis), and the 4-trainer checkpoint must remain evaluable
    through the eval CLI (reference N-rank topology:
    sheeprl/algos/ppo/ppo_decoupled.py:645-670)."""
    import glob

    import jax
    import numpy as np

    args = [
        "exp=ppo_decoupled",
        "env.id=discrete_dummy",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
        "algo.player.dedicated=True",
    ]
    dir_1t = _run_distributed(tmp_path, args, nproc=2, batch=4, subdir="logs_1t")
    dir_4t = _run_distributed(
        tmp_path, args, nproc=5, batch=1, subdir="logs_4t", timeout=600
    )
    p1 = _final_agent_params(dir_1t)
    p4 = _final_agent_params(dir_4t)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat4 = jax.tree_util.tree_leaves(p4)
    assert len(flat1) == len(flat4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    from sheeprl_tpu.cli import evaluation
    from tests.ckpt_utils import find_checkpoints

    ckpts = find_checkpoints(dir_4t)
    evaluation(
        [
            f"checkpoint_path={ckpts[-1]}",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            f"log_dir={tmp_path / 'eval_4t'}",
        ]
    )


@pytest.mark.slow
def test_dedicated_three_process_sac(tmp_path):
    """SAC dedicated topology with 2 trainers: protocol survives (deadlock /
    skew smoke at >1 trainer; off-policy sampling is rank-decorrelated so
    exact equivalence is not expected here)."""
    _run_distributed(
        tmp_path,
        [
            "exp=sac_decoupled",
            "env.id=continuous_dummy",
            "algo.learning_starts=0",
            "algo.hidden_size=16",
            "algo.player.dedicated=True",
            "algo.player.sync_every=1",
        ],
        nproc=3,
        batch=2,
        subdir="logs_sac3",
    )
