"""Tensor parallelism: the ``model`` mesh axis must change WHERE params live
without changing WHAT the train step computes.

Equivalence test (VERDICT r2 #6): one seeded DreamerV3 train step on a
2×2 data×model CPU mesh vs a single device — same losses, same updated
params.  The TP rule is fabric.param_sharding (column-sharded large 2-D
kernels, GSPMD-inserted collectives); howto/run_on_tpu.md documents the
user-facing switch ``fabric.mesh_shape={data: -1, model: K}``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel.fabric import Fabric, build_fabric

TINY = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.id=discrete_dummy",
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=4",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[]",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.dense_units=32",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "fabric.accelerator=cpu",
    "fabric.precision=32-true",
]


def _one_step(devices, mesh_shape=None, tp_min_param_size=None):
    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    import numpy as onp
    from gymnasium import spaces

    cfg = compose(TINY + [f"fabric.devices={devices}"])
    fabric = Fabric(
        devices=devices,
        accelerator="cpu",
        precision="32-true",
        mesh_shape=mesh_shape,
        tp_min_param_size=tp_min_param_size or 2**18,
    )
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), onp.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
    )
    rng = onp.random.default_rng(0)
    U, L, B = 1, 8, 4
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(onp.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(onp.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(onp.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    block = fabric.shard_batch(block, axis=2)
    params, opt_state, metrics = train_phase(
        params, opt_state, block, jax.random.PRNGKey(3), jnp.int32(0)
    )
    return fabric, jax.device_get(params), jax.device_get(metrics)


def test_tp_rule_shards_large_kernels_only():
    fab = Fabric(
        devices=4, accelerator="cpu", mesh_shape={"data": -1, "model": 2},
        tp_min_param_size=64,
    )
    tree = {
        "kernel": jnp.zeros((16, 8)),      # 2-D, big enough, 8 % 2 == 0 -> sharded
        "bias": jnp.zeros((8,)),           # 1-D -> replicated
        "small": jnp.zeros((4, 4)),        # below min size -> replicated
        "odd": jnp.zeros((16, 7)),         # 7 % 2 != 0 -> replicated
    }
    sh = fab.param_sharding(tree)
    assert sh["kernel"].spec == jax.sharding.PartitionSpec(None, "model")
    for k in ("bias", "small", "odd"):
        assert sh[k].spec == jax.sharding.PartitionSpec()


def test_tp_noop_without_model_axis():
    fab = Fabric(devices=2, accelerator="cpu")
    assert fab.model_axis is None
    sh = fab.param_sharding({"kernel": jnp.zeros((512, 512))})
    assert sh["kernel"].spec == jax.sharding.PartitionSpec()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
def test_tp_train_step_matches_single_device():
    """2×2 data×model mesh vs 1 device: seeded DV3 train step equivalence.

    Tolerance policy (measured on the jax 0.4.37 pin; derivation in
    tests/test_regression/DRIFT.md "Tensor-parallel drift"):

    * data-parallel-only (4-device ``data`` mesh, no model axis) is pure
      batch-reduction regrouping and must stay ~bit-exact (< 1e-5 measured)
      — this CONTROL isolates any looser TP drift to the model-axis
      collectives, not the mesh machinery;
    * the model axis inserts GSPMD collectives whose ~1e-7 reassociation
      noise flips near-tie discrete latent samples in the RSSM/imagination
      rollout, a chaotic O(1) amplification: smooth high-magnitude losses
      (observation/reward, |x| > 10) measured at 1.8e-3 relative → rtol
      1e-2; small KL/policy metrics measured up to 4.5e-2 → rtol 1e-1
      (a real sharding bug corrupts the smooth losses at O(1), which the
      tight tier still catches);
    * params move ≤ 2e-4 absolute — one Adam step-1 update is ±lr (1e-4)
      regardless of gradient magnitude, so a sampling flip displaces a
      param by at most ~2·lr; atol 5e-4 covers that while structural
      corruption (O(weight) displacement) still fails.
    """
    fab_tp, params_tp, metrics_tp = _one_step(
        4, mesh_shape={"data": 2, "model": 2}, tp_min_param_size=1024
    )
    # at least one kernel must actually be column-sharded, or TP wasn't on
    specs = jax.tree_util.tree_leaves(
        fab_tp.param_sharding({"w": jnp.zeros((64, 32))}, min_size=1024)
    )
    assert specs[0].spec == jax.sharding.PartitionSpec(None, "model")

    _, _, metrics_dp = _one_step(4)  # data-axis-only control
    _, params_1, metrics_1 = _one_step(1)
    for a, b in zip(jax.tree_util.tree_leaves(metrics_dp), jax.tree_util.tree_leaves(metrics_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(metrics_tp), jax.tree_util.tree_leaves(metrics_1)):
        b_arr = np.asarray(b)
        rtol = 1e-2 if np.all(np.abs(b_arr) > 10) else 1e-1
        np.testing.assert_allclose(np.asarray(a), b_arr, rtol=rtol, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(params_tp), jax.tree_util.tree_leaves(params_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=5e-4)
