"""Real 2-process host-collective coverage.

``Fabric.broadcast_object`` / ``all_gather_object`` take a pickle-pad-
allgather path that only executes when ``jax.process_count() > 1``; every
in-process test short-circuits it.  Here two actual processes are launched
with ``jax.distributed.initialize`` on the CPU backend and exercise the
multi-host code paths against each other (the same paths a TPU pod's DCN
topology uses)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    # cross-process CPU computations need explicit collectives (default
    # "none" raises "Multiprocess computations aren't implemented on the
    # CPU backend" from the first broadcast)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=2,
        process_id=int(sys.argv[1]),
    )
    from sheeprl_tpu.parallel.fabric import Fabric

    fab = Fabric(devices=1, accelerator="cpu")
    assert fab.num_processes == 2, fab.num_processes

    # broadcast: rank 0's object must arrive at rank 1 intact
    obj = {"run": "abc", "step": 7} if fab.global_rank == 0 else None
    got = fab.broadcast_object(obj, src=0)
    assert got == {"run": "abc", "step": 7}, got

    # all-gather with UNEQUAL payload sizes (exercises the pad path)
    mine = "r0" if fab.global_rank == 0 else "rank-one-longer-payload" * 10
    gathered = fab.all_gather_object(mine)
    assert gathered[0] == "r0"
    assert gathered[1] == "rank-one-longer-payload" * 10

    fab.barrier()
    print(f"rank {fab.global_rank} OK")
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_host_collectives(tmp_path):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = {
        **os.environ,
        "COORD": f"127.0.0.1:{port}",
        "JAX_PLATFORMS": "cpu",
        # each process gets its own single CPU device
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out}"
        assert f"rank {i} OK" in out
