"""Compile-once execution layer (parallel/compile.py + the recompile
detector in utils/profiler.py).

Covers the contract the layer exists to enforce:
* a second same-signature call dispatches the cached executable (no
  recompile counted);
* a changed-shape call IS counted and trips ``max_recompiles`` when
  configured;
* the AOT-compiled path is numerically equivalent to the implicit
  ``jax.jit`` path on a real algorithm update (SAC);
* ``max_recompiles`` is enforced end-to-end on the DreamerV3 and PPO train
  loops (the acceptance surface for shape drift: last-batch remainders,
  framestack variants).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sheeprl_tpu.parallel.compile import AOTFunction, CompilePool, compile_once
from sheeprl_tpu.utils.profiler import CompileMonitor, RecompileLimitExceeded


def _make(fn, **kwargs):
    return AOTFunction(fn, monitor=CompileMonitor(), **kwargs)


# ---- detector unit behavior -------------------------------------------------


def test_same_signature_does_not_recompile():
    aot = _make(lambda x: x * 2.0, name="double")
    a = aot(jnp.ones((4,)))
    b = aot(jnp.ones((4,)) + 1.0)
    np.testing.assert_allclose(np.asarray(a), 2.0)
    np.testing.assert_allclose(np.asarray(b), 4.0)
    assert aot._monitor.count("double") == 1
    assert aot.cache_size() == 1


def test_changed_shape_is_counted():
    aot = _make(lambda x: x.sum(), name="summer")
    aot(jnp.ones((4,)))
    aot(jnp.ones((8,)))  # new abstract signature -> second executable
    assert aot._monitor.count("summer") == 2
    assert len(aot._monitor.signatures("summer")) == 2


def test_changed_dtype_is_counted():
    aot = _make(lambda x: x + 1, name="inc")
    aot(jnp.ones((4,), jnp.float32))
    aot(jnp.ones((4,), jnp.int32))
    assert aot._monitor.count("inc") == 2


def test_max_recompiles_trips():
    aot = _make(lambda x: x * 1.0, name="capped", max_recompiles=0)
    aot(jnp.ones((4,)))  # first compile is free
    with pytest.raises(RecompileLimitExceeded) as exc:
        aot(jnp.ones((5,)))
    # the error must carry the signature history for diagnosis
    assert "signature history" in str(exc.value)
    # a budget of 1 allows exactly one recompile
    aot2 = _make(lambda x: x * 1.0, name="capped2", max_recompiles=1)
    aot2(jnp.ones((4,)))
    aot2(jnp.ones((5,)))
    with pytest.raises(RecompileLimitExceeded):
        aot2(jnp.ones((6,)))


def test_guard_fires_before_paying_for_the_compile():
    """Tripping the budget must not first build the offending executable."""
    calls = []

    def fn(x):
        calls.append(1)  # traced once per compile
        return x

    aot = _make(fn, name="pretrace", max_recompiles=0)
    aot(jnp.ones((2,)))
    traced = len(calls)
    with pytest.raises(RecompileLimitExceeded):
        aot(jnp.ones((3,)))
    assert len(calls) == traced  # the second shape was never traced/compiled


def test_env_default_limit(monkeypatch):
    monkeypatch.setenv("SHEEPRL_MAX_RECOMPILES", "0")
    aot = _make(lambda x: x, name="envcap")
    aot(jnp.ones((2,)))
    with pytest.raises(RecompileLimitExceeded):
        aot(jnp.ones((3,)))


def test_static_args_key_by_value():
    """Static args (by name, positionally or as kwargs) select distinct
    executables keyed by VALUE — never silently reuse across values."""
    aot = _make(
        lambda x, mode=False: x * 2.0 if mode else x + 1.0,
        name="static",
        static_argnames=("mode",),
    )
    x = jnp.ones((3,))
    np.testing.assert_allclose(np.asarray(aot(x)), 2.0)
    np.testing.assert_allclose(np.asarray(aot(x, mode=True)), 2.0)
    np.testing.assert_allclose(np.asarray(aot(x, True)), 2.0)  # positional
    np.testing.assert_allclose(np.asarray(aot(x, False)), 2.0)
    # kwarg-True and positional-True share one executable; False adds one
    assert aot._monitor.count("static") == 2


def test_tracer_arguments_inline():
    """Inside another jitted program the wrapper must inline like plain jit."""
    inner = _make(lambda x: x * 3.0, name="inner")

    @jax.jit
    def outer(x):
        return inner(x) + 1.0

    np.testing.assert_allclose(np.asarray(outer(jnp.ones((2,)))), 4.0)
    assert inner._monitor.count("inner") == 0  # inlined, never AOT-compiled


def test_donated_buffers_update_equivalence():
    """donate_argnums through the AOT path behaves like plain jit."""
    aot = _make(lambda s, d: (s + d, d), name="donate", donate_argnums=(0,))
    s, out = aot(jnp.zeros((4,)), jnp.ones((4,)))
    s, out = aot(s, out)
    np.testing.assert_allclose(np.asarray(s), 2.0)
    assert aot._monitor.count("donate") == 1


# ---- warm-up pool -----------------------------------------------------------


def test_warmup_pool_compiles_without_executing():
    ran = []

    def fn(x):
        ran.append(1)  # appended per trace, not per execution
        return x * 5.0

    aot = _make(fn, name="warm")
    pool = CompilePool(max_workers=2)
    fut = pool.submit(aot, jax.ShapeDtypeStruct((4,), jnp.float32))
    pool.join()
    assert fut.done() and aot._monitor.count("warm") == 1
    # the real call hits the warmed executable: no second compile
    out = aot(jnp.ones((4,), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 5.0)
    assert aot._monitor.count("warm") == 1
    pool.shutdown()


def test_warmup_failure_degrades_but_limit_is_hard():
    pool = CompilePool(max_workers=1)
    pool.submit_fn(lambda: (_ for _ in ()).throw(ValueError("benign")))
    pool.join()  # benign warm-up failures are swallowed

    def boom():
        raise RecompileLimitExceeded("hard")

    pool.submit_fn(boom)
    with pytest.raises(RecompileLimitExceeded):
        pool.join()
    pool.shutdown()


# ---- AOT vs implicit-jit equivalence on a real algorithm update -------------


def _tiny_sac():
    from sheeprl_tpu.algos.sac.agent import build_agent as sac_build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_train_fns
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import Fabric
    from sheeprl_tpu.utils.optim import build_optimizer

    cfg = compose(
        [
            "exp=sac",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.hidden_size=16",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    obs_dim, act_dim = 4, 2
    actor, critic, params = sac_build_agent(fabric, act_dim, cfg, obs_dim, None)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)
    opt_state = fabric.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    def plain_apply(critic_mod, cp, o, a, k):
        return critic_mod.apply(cp, o, a)

    _, train_phase = make_sac_train_fns(
        actor, critic, plain_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )
    U, bs = 2, 8
    rng = np.random.default_rng(3)
    batches = {
        "obs": jnp.asarray(rng.normal(size=(U, bs, obs_dim)).astype(np.float32)),
        "next_obs": jnp.asarray(rng.normal(size=(U, bs, obs_dim)).astype(np.float32)),
        "actions": jnp.asarray(rng.uniform(-1, 1, (U, bs, act_dim)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, bs)).astype(np.float32)),
        "terminated": jnp.zeros((U, bs), jnp.float32),
    }
    return train_phase, params, opt_state, batches


def test_aot_equals_implicit_jit_on_sac_update():
    """The AOT-compiled SAC train phase returns the same params/losses as
    the implicit-jit path — the executable runs the identical program, only
    the compile cadence differs."""
    train_phase, params, opt_state, batches = _tiny_sac()
    copy = lambda t: jax.tree.map(jnp.array, t)  # donate_argnums=(0, 1)
    k, step = jax.random.PRNGKey(9), jnp.int32(0)
    p_aot, _, losses_aot = train_phase(copy(params), copy(opt_state), batches, k, step)
    p_jit, _, losses_jit = train_phase.jitted(copy(params), copy(opt_state), batches, k, step)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6),
        (p_aot, losses_aot),
        (p_jit, losses_jit),
    )


# ---- loop-level enforcement (DreamerV3 + PPO) -------------------------------


def _monitor_count(name):
    from sheeprl_tpu.utils.profiler import COMPILE_MONITOR

    return COMPILE_MONITOR.count(name)


def test_ppo_loop_respects_max_recompiles(tmp_path):
    """A PPO dry run under a finite recompile budget completes, and its
    programs are visible in the process-global recompile detector."""
    from tests.test_algos.test_algos import standard_args
    from sheeprl_tpu.cli import run

    before = _monitor_count("ppo.train_phase")
    run(
        standard_args(
            tmp_path,
            extra=[
                "exp=ppo",
                "env=dummy",
                "env.id=discrete_dummy",
                "algo.rollout_steps=8",
                "algo.per_rank_batch_size=8",
                "algo.update_epochs=1",
                "algo.mlp_keys.encoder=[state]",
                "env.max_episode_steps=16",
                "algo.max_recompiles=4",
                "algo.run_test=False",
            ],
        )
    )
    after = _monitor_count("ppo.train_phase")
    assert 1 <= after - before <= 5  # compiled, and within budget (first free)


def test_ppo_loop_completes_under_zero_budget(tmp_path):
    """The strict compile-once contract is USABLE: a drift-free PPO dry run
    completes under max_recompiles=0.  In particular the placement
    ping-pong between the loop's initial host-committed key and the
    executable-returned one canonicalizes to ONE signature
    (_canon_placement) instead of burning a duplicate compile — shape/dtype
    drift still trips, as the unit tests above pin."""
    from tests.test_algos.test_algos import standard_args
    from sheeprl_tpu.cli import run

    run(
        standard_args(
            tmp_path,
            extra=[
                "exp=ppo",
                "env=dummy",
                "env.id=discrete_dummy",
                "algo.rollout_steps=8",
                "algo.per_rank_batch_size=8",
                "algo.update_epochs=1",
                "algo.mlp_keys.encoder=[state]",
                "env.max_episode_steps=16",
                "algo.max_recompiles=0",
                "algo.run_test=False",
            ],
        )
    )


@pytest.mark.slow
def test_dreamer_v3_loop_respects_max_recompiles(tmp_path):
    from tests.test_algos.test_algos import DV3_XS_ARGS, standard_args
    from sheeprl_tpu.cli import run

    before = _monitor_count("dreamer_v3.train_phase")
    run(
        standard_args(
            tmp_path,
            extra=[
                "exp=dreamer_v3",
                "env=dummy",
                "env.id=discrete_dummy",
                *DV3_XS_ARGS,
                "algo.max_recompiles=8",
                "algo.run_test=False",
            ],
        )
    )
    after = _monitor_count("dreamer_v3.train_phase")
    assert 1 <= after - before <= 9
    assert _monitor_count("dreamer_v3.player_step") >= 1
