"""Pod-scale fault-tolerance fabric (docs/distributed.md).

Fast cells pin the single-process halves of the DCN contracts:
:class:`DcnParamBroadcast`'s versioned staleness gate (cursors advance on
``note_applied``, never at serve time), the :class:`LearnerFront` /
:class:`PodClient` loopback round-trip (CRC-verified segments, torn
rejects, backpressure-never-drop, the ``/poll`` control plane), the
shared-checkpoint-root probe, per-rank shard verification, and the
rank-0 warning dedupe.

The ``slow`` cells launch REAL 2-process pods over the fake-DCN env
protocol (the ``SHEEPRL_FAKE_DCN`` cell branch of ``ensure_distributed``)
and pin the multi-host fabric view — global mesh over both processes,
``shard_batch``'s global-assembly semantics, cross-host reductions — and
the transport contracts ACROSS the process boundary: param fetch +
staleness gating and torn-segment rejection with the learner and actor
in different processes.
"""

import json
import os
import pickle
import subprocess
import sys
import textwrap
import urllib.request
import warnings
import zlib
from pathlib import Path

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.protocol import (
    MANIFEST_FILE,
    SHARED_ROOT_ERROR,
    probe_shared_root,
    shard_name,
    step_dir_name,
    verify_checkpoint,
    write_commit,
    write_shard,
    write_shared_root_probe,
)
from sheeprl_tpu.parallel.distributed import (
    ENV_COORD,
    ENV_FAKE,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    free_port,
    rank_zero_warn,
)
from sheeprl_tpu.parallel.topology import StalenessExceeded
from sheeprl_tpu.sebulba.queues import TornTrajectory, TrajQueue
from sheeprl_tpu.sebulba.transport import DcnParamBroadcast, LearnerFront, PodClient

REPO_ROOT = Path(__file__).resolve().parents[2]


# ---------------------------------------------------------------------------
# DcnParamBroadcast: the cross-host staleness gate
# ---------------------------------------------------------------------------


class TestDcnParamBroadcast:
    def test_publish_serves_versioned_crc_payload(self):
        b = DcnParamBroadcast([1, 2], max_staleness=2)
        params = {"w": np.arange(4.0, dtype=np.float32)}
        v = b.publish(params, version=3)
        assert v == 3 and b.version == 3
        served = b.payload_for(-1)
        assert served is not None
        payload, crc, version = served
        assert version == 3
        assert zlib.crc32(payload) & 0xFFFFFFFF == crc
        np.testing.assert_array_equal(pickle.loads(payload)["w"], params["w"])
        # nothing newer than what the caller already has -> None (HTTP 204)
        assert b.payload_for(3) is None

    def test_serving_does_not_advance_gate(self):
        b = DcnParamBroadcast([1, 2], max_staleness=0, gate_timeout_s=0.2)
        b.publish({"w": np.zeros(2)}, version=0)  # first publish seeds cursors
        assert b.gate(timeout_s=0.2) >= 0.0
        b.publish({"w": np.ones(2)}, version=1)
        # a fetch lost on the wire must not satisfy the gate: serving the
        # payload repeatedly advances nothing
        for _ in range(3):
            assert b.payload_for(0) is not None
        with pytest.raises(StalenessExceeded):
            b.gate(timeout_s=0.2)
        # a poll still reporting the OLD version records the lag but does
        # not advance the cursor
        b.note_applied(1, 0)
        assert b.staleness_max == 1
        # /poll reporting the installed version is what advances the cursor
        b.note_applied(1, 1)
        with pytest.raises(StalenessExceeded):
            b.gate(timeout_s=0.2)  # rank 2 still behind
        b.note_applied(2, 1)
        b.gate(timeout_s=0.2)

    def test_note_applied_ignores_unknown_rank(self):
        b = DcnParamBroadcast([1], max_staleness=0, gate_timeout_s=0.2)
        b.publish({"w": np.zeros(2)}, version=0)
        b.publish({"w": np.ones(2)}, version=1)
        b.note_applied(99, 1)  # not an actor rank: no cursor to advance
        with pytest.raises(StalenessExceeded):
            b.gate(timeout_s=0.2)

    def test_device_fetch_is_refused(self):
        b = DcnParamBroadcast([1])
        with pytest.raises(NotImplementedError):
            b.fetch(0)

    def test_metrics_report_dcn_bytes(self):
        b = DcnParamBroadcast([1])
        b.publish({"w": np.zeros(8, dtype=np.float32)}, version=0)
        m = b.metrics()
        assert m["Dcn/broadcast_publishes"] == 1.0
        assert m["Dcn/broadcast_bytes"] > 0.0


# ---------------------------------------------------------------------------
# LearnerFront + PodClient loopback: one process, real HTTP, real TrajQueue
# ---------------------------------------------------------------------------


@pytest.fixture()
def front_client():
    queue = TrajQueue(4, 3, None, stage=False, timeout_s=5.0)
    broadcast = DcnParamBroadcast([1], max_staleness=0, gate_timeout_s=2.0)
    front = LearnerFront(
        queue,
        broadcast,
        [1],
        host="127.0.0.1",
        port=0,
        heartbeat_grace_s=60.0,
        first_contact_grace_s=60.0,
        put_timeout_s=0.5,
    ).start()
    client = PodClient(
        front.address, 1, push_deadline_s=10.0, request_timeout_s=5.0, heartbeat_grace_s=60.0
    )
    try:
        yield queue, broadcast, front, client
    finally:
        front.stop()
        queue.close()


class TestFrontLoopback:
    def test_param_fetch_roundtrip(self, front_client):
        _, broadcast, _, client = front_client
        params = {"w": np.arange(6.0, dtype=np.float32), "b": np.zeros(2)}
        broadcast.publish(params, version=0)
        fetched = client.fetch_params(-1)
        assert fetched is not None
        got, version = fetched
        assert version == 0
        np.testing.assert_array_equal(got["w"], params["w"])
        # already current -> 204 -> None
        assert client.fetch_params(0) is None
        assert client.fetches == 1

    def test_torn_broadcast_is_refetched_never_applied(self, front_client):
        _, broadcast, _, client = front_client
        broadcast.publish({"w": np.arange(4.0)}, version=0)
        # damage the stored payload but keep the stamped CRC: exactly what
        # wire corruption past the CRC stamp looks like to the client
        with broadcast._lock:
            broadcast._payload = broadcast._payload[:-1] + b"\x00"
        assert client.fetch_params(-1) is None
        assert client.fetch_crc_rejects == 1
        broadcast.publish({"w": np.arange(4.0)}, version=1)  # clean refetch
        fetched = client.fetch_params(-1)
        assert fetched is not None and fetched[1] == 1

    def test_segment_roundtrip_with_meta(self, front_client):
        queue, _, front, client = front_client
        seg = {"obs": np.ones((3, 2), np.float32), "rew": np.zeros((3, 2), np.float32)}
        client.push_segment(seg, meta={"worker": 7, "version": 0})
        items = queue.get_many(1, timeout_s=5.0)
        got, meta = items[0]
        np.testing.assert_array_equal(got["obs"], seg["obs"])
        assert meta["worker"] == 7
        assert front.segments_accepted == 1 and client.segments_pushed == 1

    def test_torn_segment_rejected_never_enqueued(self, front_client):
        queue, _, front, client = front_client
        # wrong leading (time) axis: structurally torn — the queue's own
        # validation holds across the process boundary, and retrying the
        # same buffer can never succeed, so the client fails loudly NOW
        with pytest.raises(TornTrajectory):
            client.push_segment({"obs": np.ones((2, 2), np.float32)})
        assert front.segments_rejected == 1
        assert queue.total_put == 0 and queue.qsize() == 0

    def test_wire_crc_mismatch_is_rejected_with_409(self, front_client):
        queue, _, front, client = front_client
        payload = pickle.dumps({"obs": np.ones((3, 2), np.float32)})
        req = urllib.request.Request(
            f"http://{front.address}/segment",
            data=payload,
            headers={"X-Sheeprl-CRC32": "12345", "X-Sheeprl-Rank": "1"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5.0)
        assert exc_info.value.code == 409
        assert b"crc mismatch" in exc_info.value.read()
        assert front.segments_rejected == 1 and queue.total_put == 0
        # the same segment with its true CRC goes through: a torn wire
        # costs a retry, never a segment
        client.push_segment({"obs": np.ones((3, 2), np.float32)})
        assert front.segments_accepted == 1

    def test_backpressure_retries_until_drained_never_drops(self, front_client):
        queue, _, front, client = front_client
        for _ in range(queue.capacity):
            queue.put({"obs": np.zeros((3, 2), np.float32)})
        import threading
        import time as _time

        def drain():
            _time.sleep(0.8)
            queue.get_many(2, timeout_s=5.0)

        t = threading.Thread(target=drain)
        t.start()
        client.push_segment({"obs": np.ones((3, 2), np.float32)})  # rides a 503 retry
        t.join()
        assert front.backpressured >= 1
        assert front.segments_accepted == 1
        assert queue.total_put == queue.capacity + 1  # nothing dropped

    def test_poll_control_plane(self, front_client):
        _, broadcast, front, client = front_client
        broadcast.publish({"w": np.zeros(2)}, version=0)
        resp = client.poll(0)
        assert resp == {
            "version": 0,
            "commit_step": -1,
            "commit_steps": [],
            "preempt": False,
            "done": False,
        }
        front.set_commit(7)
        assert client.poll(0)["commit_step"] == 7
        # back-to-back announcements accumulate instead of coalescing —
        # a fast learner's async commit manager can announce two saves
        # between actor polls, and BOTH need shards
        front.set_commit(14)
        resp = client.poll(0)
        assert resp["commit_step"] == 14
        assert resp["commit_steps"] == [7, 14]
        # gate clears off the poll's applied_version report
        broadcast.publish({"w": np.ones(2)}, version=1)
        client.poll(1)
        broadcast.gate(timeout_s=1.0)
        # the actor's preemption latch crosses to the learner...
        assert not front.actor_latched
        client.poll(1, latched=True)
        assert front.actor_latched
        # ...and reflects back to every cell as a pod-wide preempt
        assert client.poll(1)["preempt"] is True
        # per-cell hub snapshots land rank-prefixed in the learner stream
        client.poll(1, hub={"Loss/x": 2.0, "rank1/Game/y": 3.0})
        metrics = front.metrics()
        assert metrics["rank1/Loss/x"] == 2.0
        assert metrics["rank1/Game/y"] == 3.0  # no double prefix
        front.set_done()
        assert client.poll(1)["done"] is True

    def test_done_front_tells_pushers_to_stop(self, front_client):
        queue, _, front, client = front_client
        from sheeprl_tpu.serve.batcher import ServiceStopped

        front.set_done()
        queue.close()
        with pytest.raises(ServiceStopped):
            client.push_segment({"obs": np.ones((3, 2), np.float32)})

    def test_goodbye_completes_shutdown(self, front_client):
        _, _, front, client = front_client
        assert not front.wait_goodbyes(0.2)
        client.goodbye("rollout complete")
        assert front.wait_goodbyes(5.0)


# ---------------------------------------------------------------------------
# Shared checkpoint root: fail fast, name the missing ranks
# ---------------------------------------------------------------------------


class TestSharedRoot:
    def test_rank_nonzero_fails_fast_without_probe(self, tmp_path):
        with pytest.raises(RuntimeError) as exc_info:
            probe_shared_root(tmp_path, rank=1, timeout_s=0.3)
        assert SHARED_ROOT_ERROR in str(exc_info.value)
        assert "shared storage" in SHARED_ROOT_ERROR

    def test_probe_passes_once_rank_zero_wrote(self, tmp_path):
        write_shared_root_probe(tmp_path)
        probe_shared_root(tmp_path, rank=1, timeout_s=0.3)  # no raise

    def _commit_two_rank_checkpoint(self, root, step=10):
        step_dir = root / step_dir_name(step)
        step_dir.mkdir(parents=True)
        for rank in range(2):
            write_shard(step_dir, rank, {"pod_rank": rank, "policy_step": step})
        assert write_commit(step_dir, step=step, world=2, timeout_s=5.0)
        return step_dir

    def test_verify_reports_which_rank_shard_is_missing(self, tmp_path):
        step_dir = self._commit_two_rank_checkpoint(tmp_path)
        assert verify_checkpoint(step_dir) == []
        (step_dir / shard_name(1)).unlink()
        problems = verify_checkpoint(step_dir)
        assert problems and any("(rank 1)" in p for p in problems)
        assert not any("(rank 0)" in p for p in problems)

    def test_verify_reports_unlisted_ranks(self, tmp_path):
        step_dir = self._commit_two_rank_checkpoint(tmp_path)
        manifest = json.loads((step_dir / MANIFEST_FILE).read_text())
        manifest["world"] = 3  # a rank whose shard the manifest never saw
        (step_dir / MANIFEST_FILE).write_text(json.dumps(manifest))
        problems = verify_checkpoint(step_dir)
        assert any("ranks [2] are not listed" in p for p in problems)


# ---------------------------------------------------------------------------
# rank_zero_warn: one copy per pod, once per process
# ---------------------------------------------------------------------------


class TestRankZeroWarn:
    def test_rank_zero_warns_once_per_key(self, monkeypatch):
        monkeypatch.setenv(ENV_PROCESS_ID, "0")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            rank_zero_warn("pod-wide fact", key="test.dedupe.a")
            rank_zero_warn("pod-wide fact (again)", key="test.dedupe.a")
        assert len(caught) == 1
        assert "pod-wide fact" in str(caught[0].message)

    def test_nonzero_rank_is_silent(self, monkeypatch):
        monkeypatch.setenv(ENV_PROCESS_ID, "3")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            rank_zero_warn("pod-wide fact", key="test.dedupe.b")
        assert caught == []


# ---------------------------------------------------------------------------
# Real 2-process pods over the fake-DCN env protocol
# ---------------------------------------------------------------------------


def _run_pod_cells(worker_src: str, tmp_path: Path, timeout: float = 240.0):
    """Launch ``worker_src`` as 2 fake-DCN cells (the exact env protocol
    ``PodSupervisor._spawn`` / ``launch_fake_dcn`` set) and return the
    combined outputs after asserting both exited 0."""
    script = tmp_path / "cell.py"
    script.write_text(worker_src)
    coord = f"127.0.0.1:{free_port()}"
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            {
                ENV_FAKE: "2",
                ENV_PROCESS_ID: str(rank),
                ENV_NUM_PROCESSES: "2",
                ENV_COORD: coord,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PYTHONPATH": str(REPO_ROOT),
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), str(rank)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=str(tmp_path),
            )
        )
    outputs = []
    for rank, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=timeout)
        outputs.append(out)
    for rank, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"cell {rank} failed:\n{out}"
        assert f"rank {rank} OK" in out, f"cell {rank} never reached OK:\n{out}"
    return outputs


_MESH_WORKER = textwrap.dedent(
    """
    import sys

    import numpy as np

    rank = int(sys.argv[1])

    from sheeprl_tpu.parallel.distributed import ensure_distributed

    assert ensure_distributed({}) == "cell"

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2
    assert jax.process_index() == rank

    from sheeprl_tpu.parallel.fabric import Fabric

    fab = Fabric(devices="auto", accelerator="cpu")
    # the global mesh spans BOTH processes; each contributes one device
    assert fab.num_processes == 2
    assert fab.world_size == 2, fab.world_size
    assert fab.local_world_size == 1
    assert fab.global_rank == rank
    assert fab.is_global_zero == (rank == 0)

    # shard_batch assembles the global batch from per-process locals:
    # each process feeds its OWN 4-row shard, the global array is 8 rows
    local = np.full((4, 3), float(rank), dtype=np.float32)
    g = fab.shard_batch({"x": local})["x"]
    assert g.shape == (8, 3), g.shape
    shards = list(g.addressable_shards)
    assert len(shards) == 1
    np.testing.assert_array_equal(np.asarray(shards[0].data), local)

    # a jitted reduction over the global array is a REAL cross-host
    # collective: 4*3 zeros from rank 0 + 4*3 ones from rank 1
    total = jax.jit(jnp.sum)(g)
    assert float(np.asarray(total.addressable_data(0))) == 12.0

    # copy_to pulls the process-local view to host as a true copy
    host = fab.copy_to({"x": np.asarray(shards[0].data)}, fab.host_device)
    np.testing.assert_array_equal(np.asarray(host["x"]), local)

    # host-object collectives ride the coordinator KV store on CPU pods
    gathered = fab.all_gather_object({"rank": rank})
    assert [g["rank"] for g in gathered] == [0, 1]
    word = fab.broadcast_object("from-zero" if rank == 0 else None, src=0)
    assert word == "from-zero"
    fab.barrier()

    print(f"rank {rank} OK")
    """
)


_TRANSPORT_WORKER = textwrap.dedent(
    """
    import sys
    import time

    import numpy as np

    rank = int(sys.argv[1])

    from sheeprl_tpu.parallel.distributed import ensure_distributed

    assert ensure_distributed({}) == "cell"

    deadline = time.monotonic() + 120.0

    if rank == 0:
        from sheeprl_tpu.sebulba.queues import TrajQueue
        from sheeprl_tpu.sebulba.transport import (
            DcnParamBroadcast,
            LearnerFront,
            publish_front_address,
        )

        queue = TrajQueue(4, 3, None, stage=False, timeout_s=60.0)
        broadcast = DcnParamBroadcast([1], max_staleness=0, gate_timeout_s=90.0)
        front = LearnerFront(
            queue, broadcast, [1], host="127.0.0.1",
            heartbeat_grace_s=60.0, first_contact_grace_s=90.0,
        ).start()
        publish_front_address(front.address)
        broadcast.publish({"w": np.arange(4.0, dtype=np.float32)}, version=0)
        front.wait_for_cells(90.0)

        # the actor pushed one torn segment first (rejected, never
        # enqueued) and one good one (the only thing the queue ever saw)
        seg, meta = queue.get_many(1, timeout_s=90.0)[0]
        assert seg["obs"].shape == (3, 2), seg["obs"].shape
        assert meta["worker"] == 7
        assert front.segments_rejected >= 1
        assert front.segments_accepted == 1
        assert queue.total_put == 1

        # the cross-host staleness gate: v1 with max_staleness=0 blocks
        # the learner until the remote cell REPORTS it applied v1
        broadcast.publish({"w": np.arange(4.0, dtype=np.float32) + 1.0}, version=1)
        broadcast.gate()
        assert broadcast.staleness_max >= 1

        front.set_done()
        assert front.wait_goodbyes(60.0)
        front.stop()
        queue.close()
    else:
        from sheeprl_tpu.sebulba.queues import TornTrajectory
        from sheeprl_tpu.sebulba.transport import PodClient, lookup_front_address

        client = PodClient(
            lookup_front_address(timeout_s=90.0), 1,
            push_deadline_s=60.0, request_timeout_s=10.0, heartbeat_grace_s=60.0,
        )
        fetched = None
        while fetched is None and time.monotonic() < deadline:
            fetched = client.fetch_params(-1)
            if fetched is None:
                time.sleep(0.1)
        assert fetched is not None, "never fetched initial params"
        params, applied = fetched
        assert applied == 0
        np.testing.assert_array_equal(params["w"], np.arange(4.0, dtype=np.float32))

        # structurally torn segment: rejected across the process boundary
        try:
            client.push_segment({"obs": np.ones((2, 2), np.float32)}, meta={"worker": 7})
            raise AssertionError("torn segment was accepted")
        except TornTrajectory:
            pass
        client.push_segment({"obs": np.ones((3, 2), np.float32)}, meta={"worker": 7})

        # control loop: poll, fetch what the learner published, report it
        while time.monotonic() < deadline:
            resp = client.poll(applied)
            if resp is None:
                time.sleep(0.1)
                continue
            if resp["version"] > applied:
                got = client.fetch_params(applied)
                if got is not None:
                    params, applied = got
                    np.testing.assert_array_equal(
                        params["w"], np.arange(4.0, dtype=np.float32) + 1.0
                    )
                continue
            if resp["done"]:
                break
            time.sleep(0.1)
        assert applied == 1, f"never applied v1 (applied={applied})"
        client.goodbye("test complete")

    print(f"rank {rank} OK")
    """
)


@pytest.mark.slow
class TestFakeDcnPod:
    def test_two_process_global_mesh_semantics(self, tmp_path):
        _run_pod_cells(_MESH_WORKER, tmp_path)

    def test_cross_host_broadcast_gate_and_torn_segments(self, tmp_path):
        _run_pod_cells(_TRANSPORT_WORKER, tmp_path)
