"""Unit tests for the supervisor's failure triage
(sheeprl_tpu/supervisor/classify.py): transient infra restarts, the same
fatal step twice is deterministic, malformed postmortems degrade safely."""

import json

from sheeprl_tpu.supervisor.classify import (
    DETERMINISTIC,
    DIVERGED,
    PREEMPTED,
    SUCCESS,
    TRANSIENT,
    classify,
    crash_error,
    load_postmortem,
)


def _pm(reason="exception", error="InjectedFault: boom", last_step=37, **extra):
    doc = {
        "schema": "sheeprl.postmortem/1",
        "reason": reason,
        "last_step": last_step,
        "events": [{"kind": "span"}, {"kind": "crash", "error": error}],
    }
    doc.update(extra)
    return doc


class TestVerdicts:
    def test_clean_exit_is_success(self):
        v = classify(0, None)
        assert v.kind == SUCCESS and not v.restartable

    def test_kill_9_is_transient_without_signature(self):
        # "kill -9 => restart": a signal death carries NO fatal signature,
        # so it can never open the breaker, only burn the budget
        v = classify(-9, None)
        assert v.kind == TRANSIENT and v.restartable
        assert v.signature is None
        assert "SIGKILL" in v.reason

    def test_hang_overrides_exit_status(self):
        # a watchdog-SIGTERM'd child often exits 0 through its preemption
        # save — the supervisor's own hang verdict must win
        v = classify(0, _pm(), hung=True)
        assert v.kind == TRANSIENT
        assert v.signature == ("hang", 37)

    def test_exception_carries_fatal_signature(self):
        v = classify(1, _pm())
        assert v.kind == TRANSIENT and v.restartable
        assert v.signature == ("InjectedFault: boom", 37)

    def test_preemption_is_restartable_without_signature(self):
        v = classify(1, _pm(reason="preemption"))
        assert v.kind == PREEMPTED and v.restartable
        assert v.signature is None

    def test_preempted_child_exiting_zero_is_not_success(self):
        # the latch makes a preempted run exit 0 through its final
        # committed save — the preemption postmortem must win over the
        # clean exit status, or the supervisor reports an incomplete run
        # as done and never restarts it
        v = classify(0, _pm(reason="preemption"))
        assert v.kind == PREEMPTED and v.restartable
        # ...while a genuinely completed run (no fresh postmortem) stays
        # success
        assert classify(0, None).kind == SUCCESS

    def test_divergence_is_flagged_and_signed(self):
        v = classify(1, _pm(error="DivergenceError: diverged at step 99", last_step=99))
        assert v.kind == DIVERGED and v.restartable
        assert v.signature == ("DivergenceError: diverged at step 99", 99)

    def test_missing_postmortem_is_transient_unsigned(self):
        v = classify(1, None)
        assert v.kind == TRANSIENT and v.signature is None
        assert "missing/malformed" in v.reason

    def test_classify_never_emits_deterministic_itself(self):
        # DETERMINISTIC is the supervisor's breaker decision (signature
        # repetition), not a single-episode verdict
        for v in (classify(1, _pm()), classify(-9, None), classify(1, None)):
            assert v.kind != DETERMINISTIC


class TestPostmortemParsing:
    def test_load_missing(self, tmp_path):
        assert load_postmortem(None) is None
        assert load_postmortem(str(tmp_path / "nope.json")) is None

    def test_load_malformed_json(self, tmp_path):
        p = tmp_path / "postmortem.json"
        p.write_text("{ not json")
        assert load_postmortem(str(p)) is None

    def test_load_wrong_schema(self, tmp_path):
        p = tmp_path / "postmortem.json"
        p.write_text(json.dumps({"schema": "other/1", "reason": "exception"}))
        assert load_postmortem(str(p)) is None

    def test_load_roundtrip(self, tmp_path):
        p = tmp_path / "postmortem.json"
        p.write_text(json.dumps(_pm()))
        doc = load_postmortem(str(p))
        assert doc is not None and doc["reason"] == "exception"

    def test_crash_error_picks_newest_crash_event(self):
        doc = _pm()
        doc["events"].append({"kind": "crash", "error": "second"})
        assert crash_error(doc) == "second"

    def test_crash_error_absent(self):
        assert crash_error({"events": [{"kind": "span"}]}) is None
        assert crash_error({}) is None
