"""Supervisor loop tests over scripted child processes (no training):
restart-on-transient, breaker-on-repeat, budget exhaustion, hang watchdog.

Each test builds a tiny ``python -c`` child that crashes/hangs/succeeds on
cue (a marker file counts launches) and writes postmortem.json where a
real run would (``<log_dir>/<root_dir>/<run>/version_0/``)."""

import json
import os
import sys
import textwrap

import pytest

from sheeprl_tpu.supervisor import (
    EXIT_BREAKER,
    EXIT_BUDGET,
    EXIT_OK,
    Supervisor,
)
from sheeprl_tpu.utils.structured import dotdict


def make_supervisor(tmp_path, child_script, scfg=None, argv=None):
    cfg = dotdict(
        {
            "supervisor": {
                "max_restarts": 3,
                "backoff_base_s": 0.01,
                "poll_interval_s": 0.1,
                "kill_grace_s": 5.0,
                "introspect": False,
                **(scfg or {}),
            },
            "log_dir": str(tmp_path),
            "root_dir": "exp",
        }
    )
    return Supervisor(
        cfg,
        list(argv or ["exp=fake"]),
        child_cmd=lambda child_argv: [sys.executable, "-c", child_script, *child_argv],
        handle_signals=False,
    )


def episodes_of(sup):
    with open(sup.audit_path) as f:
        return [json.loads(line) for line in f]


def child_source(tmp_path, body):
    """A child script with RUN counting + postmortem helpers in scope."""
    return textwrap.dedent(
        f"""
        import json, os, sys, time
        ROOT = {str(tmp_path)!r}
        MARKER = os.path.join(ROOT, "launches")
        launches = int(open(MARKER).read()) if os.path.exists(MARKER) else 0
        open(MARKER, "w").write(str(launches + 1))

        def write_postmortem(doc, run="run_a"):
            run_dir = os.path.join(ROOT, "exp", run, "version_0")
            os.makedirs(run_dir, exist_ok=True)
            with open(os.path.join(run_dir, "postmortem.json"), "w") as f:
                if isinstance(doc, str):
                    f.write(doc)
                else:
                    json.dump(doc, f)
        """
    ) + textwrap.dedent(body)


PM_CRASH = (
    '{"schema": "sheeprl.postmortem/1", "reason": "exception", "last_step": 37,'
    ' "events": [{"kind": "crash", "error": "InjectedFault: boom"}]}'
)


class TestRestart:
    def test_crash_once_then_succeed(self, tmp_path):
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                f"""
                if launches == 0:
                    write_postmortem({PM_CRASH!r})
                    sys.exit(1)
                sys.exit(0)
                """,
            ),
        )
        assert sup.run() == EXIT_OK
        eps = episodes_of(sup)
        assert [e["classification"] for e in eps] == ["transient", "success"]
        assert eps[0]["action"] == "restart" and eps[1]["action"] == "done"

    def test_restart_forces_auto_resume(self, tmp_path):
        out = tmp_path / "argv.json"
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                f"""
                if launches == 0:
                    sys.exit(1)
                json.dump(sys.argv[1:], open({str(out)!r}, "w"))
                sys.exit(0)
                """,
            ),
            argv=["exp=fake", "algo.total_steps=64"],
        )
        assert sup.run() == EXIT_OK
        relaunch_argv = json.load(open(out))
        # user argv preserved, resume appended LAST so it wins composition
        assert relaunch_argv[0] == "exp=fake"
        assert relaunch_argv[-1] == "checkpoint.resume_from=auto"

    def test_preempted_child_restarts_despite_rc_zero(self, tmp_path):
        # external preemption: the child exits 0 through its final save
        # and leaves a reason=preemption postmortem — the supervisor must
        # resume it, not call the run done
        pm = (
            '{"schema": "sheeprl.postmortem/1", "reason": "preemption",'
            ' "last_step": 20, "events": []}'
        )
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                f"""
                if launches == 0:
                    write_postmortem({pm!r})
                    sys.exit(0)
                sys.exit(0)
                """,
            ),
        )
        assert sup.run() == EXIT_OK
        eps = episodes_of(sup)
        assert [e["classification"] for e in eps] == ["preempted", "success"]
        assert eps[0]["action"] == "restart"

    def test_kill_9_restarts(self, tmp_path):
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                """
                if launches == 0:
                    os.kill(os.getpid(), 9)
                sys.exit(0)
                """,
            ),
        )
        assert sup.run() == EXIT_OK
        eps = episodes_of(sup)
        assert eps[0]["returncode"] == -9
        assert eps[0]["classification"] == "transient"
        assert eps[0]["signature"] is None  # signals never open the breaker
        assert eps[1]["classification"] == "success"


class TestBreaker:
    def test_same_fatal_signature_twice_opens_breaker(self, tmp_path):
        # deterministic crash: identical (error, last_step) every episode —
        # the breaker must stop after breaker_threshold=2, NOT burn the
        # whole restart budget (max_restarts=3)
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                f"""
                write_postmortem({PM_CRASH!r}, run="run_%d" % launches)
                sys.exit(1)
                """,
            ),
        )
        assert sup.run() == EXIT_BREAKER
        eps = episodes_of(sup)
        assert len(eps) == 2
        assert eps[0]["classification"] == "transient"
        assert eps[1]["classification"] == "deterministic"
        assert "circuit breaker open" in eps[1]["reason"]
        # the postmortem reason is surfaced in the verdict chain
        assert eps[1]["signature"] == ["InjectedFault: boom", 37]

    def test_different_steps_do_not_open_breaker(self, tmp_path):
        # same error string but the fatal step ADVANCES (the resume made
        # progress): transient every time, bounded by the budget instead
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                """
                doc = {"schema": "sheeprl.postmortem/1", "reason": "exception",
                       "last_step": 10 * (launches + 1),
                       "events": [{"kind": "crash", "error": "InjectedFault: boom"}]}
                write_postmortem(doc, run="run_%d" % launches)
                sys.exit(1)
                """,
            ),
            scfg={"max_restarts": 2},
        )
        assert sup.run() == EXIT_BUDGET
        eps = episodes_of(sup)
        assert [e["classification"] for e in eps] == ["transient"] * 3
        assert eps[-1]["action"] == "budget-exhausted"


class TestBudget:
    def test_malformed_postmortem_is_transient_with_budget(self, tmp_path):
        # a child that dies without intelligible evidence (OOM-killer,
        # segfault before the dump): restart, but under the budget — and
        # never the breaker (no signature to repeat)
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                """
                write_postmortem("{ not json", run="run_%d" % launches)
                sys.exit(1)
                """,
            ),
            scfg={"max_restarts": 2},
        )
        assert sup.run() == EXIT_BUDGET
        eps = episodes_of(sup)
        assert len(eps) == 3  # initial + 2 restarts
        assert all(e["classification"] == "transient" for e in eps)
        assert all(e["signature"] is None for e in eps)


class TestHangWatchdog:
    @pytest.mark.slow
    def test_silent_child_is_killed_and_restarted(self, tmp_path):
        # introspect armed but the child never prints a URL: the
        # first-heartbeat timeout declares a hang, SIGTERM lands (the
        # sleeping child dies with -15), the relaunch succeeds
        sup = make_supervisor(
            tmp_path,
            child_source(
                tmp_path,
                """
                if launches == 0:
                    time.sleep(300)
                sys.exit(0)
                """,
            ),
            scfg={
                "introspect": True,
                "first_heartbeat_timeout_s": 1.0,
                "poll_interval_s": 0.2,
                "kill_grace_s": 5.0,
            },
        )
        assert sup.run() == EXIT_OK
        eps = episodes_of(sup)
        assert eps[0]["hung"] is True
        assert eps[0]["classification"] == "transient"
        assert eps[1]["classification"] == "success"
