#!/usr/bin/env bash
# The one-command CPU test gate (runs in CI — .github/workflows/cpu-tests.yaml —
# and locally).  Parity role model: the reference's pinned suite
# (/root/reference/.github/workflows/cpu-tests.yaml:25-65 + tests/run_tests.py).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "=== stage 1/5: unit + E2E dry-run suite ==="
python -m pytest tests/ -x -q --ignore=tests/test_regression --ignore=tests/test_checkpoint

echo "=== stage 2/5: fault-tolerant checkpointing (commit protocol + SIGTERM/resume drill) ==="
python -m pytest tests/test_checkpoint -q

echo "=== stage 3/5: numeric regression (goldens + reference fixture) ==="
python -m pytest tests/test_regression -q

echo "=== stage 4/5: multichip dryrun (virtual 8-device mesh) ==="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "=== stage 5/5: policy-serving smoke (HTTP server + batched requests + clean shutdown) ==="
python tests/serve_smoke.py

echo "CI gate: ALL GREEN"
