#!/usr/bin/env bash
# The one-command CPU test gate (runs in CI — .github/workflows/cpu-tests.yaml —
# and locally).  Parity role model: the reference's pinned suite
# (/root/reference/.github/workflows/cpu-tests.yaml:25-65 + tests/run_tests.py).
#
# Every stage runs under its own WALL BUDGET (`timeout`): a wedged stage —
# exactly the failure class the resilience layer exists for — kills that
# stage with rc=124 instead of hanging the whole gate.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "=== stage 1/18: unit + E2E dry-run suite (budget 1500s) ==="
timeout -k 15 1500 python -m pytest tests/ -x -q \
  --ignore=tests/test_regression --ignore=tests/test_checkpoint \
  --ignore=tests/test_resilience

echo "=== stage 2/18: fault-tolerant checkpointing (commit protocol + SIGTERM/resume drill) (budget 420s) ==="
timeout -k 15 420 python -m pytest tests/test_checkpoint -q

echo "=== stage 3/18: chaos drills (fault injection: env storm, SIGKILL+quarantine resume, serve under faults) (budget 600s) ==="
timeout -k 15 600 python -m pytest tests/test_resilience -q

echo "=== stage 4/18: numeric regression (goldens + reference fixture) (budget 600s) ==="
timeout -k 15 600 python -m pytest tests/test_regression -q

echo "=== stage 5/18: multichip dryrun (virtual 8-device mesh) (budget 900s) ==="
timeout -k 15 900 python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "=== stage 6/18: 2-D (data x model) mesh training cell + compile budget (budget 600s) ==="
# dreamer_v3 end-to-end through the CLI on a 2x4 fake-device mesh: the
# partition-rules (TP) path with the recompile detector as a hard gate —
# algo.max_recompiles=1 means each compile-once program (train phase, player
# step) may compile at most twice (first compile free + the prefill/train
# signature split); a TP path that regressed to recompile-per-step dies here.
timeout -k 15 600 python - <<'PY'
from sheeprl_tpu.cli import run
run([
    "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy",
    "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]",
    "algo.horizon=4", "algo.dense_units=16", "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4",
    "algo.per_rank_batch_size=4", "algo.per_rank_sequence_length=8",
    "algo.learning_starts=16", "algo.total_steps=32", "algo.replay_ratio=0.5",
    "algo.max_recompiles=1", "algo.run_test=False",
    "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
    "fabric.devices=8", "fabric.accelerator=cpu",
    "fabric.mesh_shape={data: 2, model: 4}",
    "checkpoint.every=0", "checkpoint.save_last=False", "buffer.memmap=False",
    "metric.log_level=0", "log_dir=/tmp/run_ci_tp_logs", "print_config=False",
])
print("stage 6/18 OK: dreamer_v3 trained on a 2x4 data x model mesh within the compile budget")
PY

echo "=== stage 7/18: policy-serving smoke (HTTP server + batched requests + clean shutdown) (budget 600s) ==="
timeout -k 15 600 python tests/serve_smoke.py

echo "=== stage 8/18: fault-injection zero-overhead gate (empty plan steady-state within 2%) (budget 600s) ==="
timeout -k 15 600 env BENCH_TARGET=fault_overhead python bench.py

echo "=== stage 9/18: zero-copy device replay (dreamer_v3 + sac, transfer guard armed) (budget 900s) ==="
# Coupled dreamer_v3 and sac train SHORT real runs (not dryruns: the guard
# only means something once steady-state windows exist) with the
# device-resident replay forced on, jax.transfer_guard("disallow") armed
# around every post-warmup train window (buffer.transfer_guard=true), and
# the recompile budget at 1 — a steady state that ships a batch H2D, or a
# cursor that churns the executable signature, dies here red.
timeout -k 15 900 python - <<'PY'
from sheeprl_tpu.cli import run
common = [
    "env=dummy", "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
    "fabric.devices=2", "fabric.accelerator=cpu",
    "buffer.memmap=False", "buffer.size=1024", "buffer.device=True",
    "buffer.transfer_guard=True", "checkpoint.every=0", "checkpoint.save_last=False",
    "metric.log_level=0", "algo.max_recompiles=1", "algo.run_test=False",
    "print_config=False",
]
run([
    "exp=dreamer_v3", "env.id=discrete_dummy", "env.action_repeat=1",
    "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]",
    "algo.horizon=4", "algo.dense_units=16", "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4",
    "algo.per_rank_batch_size=4", "algo.per_rank_sequence_length=8",
    "algo.learning_starts=16", "algo.total_steps=64", "algo.replay_ratio=0.5",
    "log_dir=/tmp/run_ci_replay_dv3",
] + common)
print("stage 9 dv3 OK: zero-copy steady state under transfer guard")
run([
    "exp=sac", "env.id=continuous_dummy",
    "algo.learning_starts=16", "algo.total_steps=96", "algo.replay_ratio=0.5",
    "algo.per_rank_batch_size=8",
    "log_dir=/tmp/run_ci_replay_sac",
] + common)
print("stage 9/18 OK: dreamer_v3 + sac trained zero-copy under the transfer guard")
PY

echo "=== stage 10/18: scenario matrix (every algo x {cpu-gym, jax-env, dummy} x {coupled, decoupled}) (budget 1500s) ==="
# The enforced grid from ROADMAP item 5: each cell is an end-to-end dryrun
# under algo.max_recompiles=1 (compile budget) and a per-cell wall budget
# (tests/scenario_matrix.py prints the full coverage table, including the
# pruned cells and why).  The jax column drives BOTH rollout modes of the
# on-policy loops: Anakin fused and the JaxToGymAdapter fallback.
timeout -k 15 1500 python tests/scenario_matrix.py

echo "=== stage 11/18: sebulba actor-learner topology (2-actor/2-learner fake-device split) (budget 600s) ==="
# ISSUE 12: decoupled PPO trains end-to-end through the Sebulba device
# split — env-worker threads feeding batched AOT actor inference on the
# actor group, the learner sub-mesh consuming the device-resident
# trajectory queue, learner->actor D2D param broadcast — under
# algo.max_recompiles=1 (executable-signature churn in the actor ladder,
# the learner phase, or the broadcast dies here red).
timeout -k 15 600 python - <<'SEB'
from sheeprl_tpu.cli import run
run([
    "exp=ppo_decoupled", "env=dummy", "env.id=discrete_dummy",
    "env.max_episode_steps=16", "env.num_envs=4", "env.sync_env=True",
    "env.capture_video=False",
    "topology=sebulba", "topology.actor_devices=2", "topology.learner_devices=2",
    "topology.env_workers=2",
    "fabric.devices=4", "fabric.accelerator=cpu",
    "algo.rollout_steps=4", "algo.per_rank_batch_size=8",
    "algo.update_epochs=1", "algo.total_steps=64",
    "algo.mlp_keys.encoder=[state]", "algo.max_recompiles=1",
    "algo.run_test=False", "checkpoint.every=0", "checkpoint.save_last=False",
    "buffer.memmap=False", "metric.log_level=1", "metric.log_every=1",
    "print_config=False", "log_dir=/tmp/run_ci_sebulba",
])
print("stage 11/18 OK: ppo_decoupled trained through the sebulba 2-actor/2-learner split within the compile budget")
SEB

echo "=== stage 12/18: telemetry drill (live /metrics + /v1/phase scrape, fault kill, postmortem evidence) (budget 600s) ==="
# ISSUE 13: a short dv3 run with telemetry.introspect.port armed is scraped
# MID-RUN (/metrics Prometheus exposition + /v1/phase breakdown summing to
# ~1.0), then a planted env.step fault kills it and the run dir must hold a
# well-formed postmortem.json containing the injected-fault event.
timeout -k 15 600 python tests/telemetry_drill.py

echo "=== stage 13/18: supervisor drill (fatal fault -> classified restart -> auto-resume -> full step count) (budget 600s) ==="
# ISSUE 14: a supervised SAC run is killed mid-run by a planted env.step
# fault; the supervisor classifies the crash off postmortem.json, restarts
# with checkpoint.resume_from=auto, and the resumed run completes with the
# FULL configured step count — the audit trail (supervisor_log.jsonl) and
# the monotone committed-checkpoint history are asserted.  The default-on
# health-sentinel cost gate runs as part of bench (--mode health_overhead,
# asserted <2% in tests/test_resilience/test_health.py's marker-free units;
# the full interleaved A/B gate is stage-8-style and runs here too).
timeout -k 15 600 python tests/supervisor_drill.py
timeout -k 15 600 env BENCH_TARGET=health_overhead python bench.py

echo "=== stage 14/18: graftlint static analysis (zero unsuppressed findings, strict baseline) (budget 120s) ==="
# ISSUE 15: the JAX-law analyzer over the whole package — use-after-donate
# (the PR 7/PR 14 bug class), trace purity, PRNG discipline, and the
# config/fault-site/metric registries.  --strict also fails on STALE
# baseline entries: a fixed finding must take its ledger entry with it.
# Wall is additionally tracked by `bench.py --mode lint` (<60s gate).
timeout -k 15 120 python -m sheeprl_tpu.analysis --strict

echo "=== stage 15/18: pipelined world-model training cell (2-stage x 2-data mesh) (budget 600s) ==="
# ISSUE 16: dreamer_v3 end-to-end through the CLI with the pipeline group
# live — a pipeline mesh axis composing with the partition rules, the
# world-model update running as the in-trace 1F1B microbatch schedule
# (pipeline=2stage: S=2, M=4) — under algo.max_recompiles=1: a schedule
# that broke the compile-once law or leaked an H2D transfer dies here red.
timeout -k 15 600 python - <<'PIPE'
from sheeprl_tpu.cli import run
run([
    "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy",
    "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]",
    "algo.horizon=4", "algo.dense_units=16", "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4",
    "algo.per_rank_batch_size=4", "algo.per_rank_sequence_length=8",
    "algo.learning_starts=16", "algo.total_steps=32", "algo.replay_ratio=0.5",
    "algo.max_recompiles=1", "algo.run_test=False",
    "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
    "fabric.devices=4", "fabric.accelerator=cpu",
    "fabric.mesh_shape={data: 2, pipeline: 2}",
    "pipeline=2stage",
    "checkpoint.every=0", "checkpoint.save_last=False", "buffer.memmap=False",
    "metric.log_level=0", "log_dir=/tmp/run_ci_pipeline_logs", "print_config=False",
])
print("stage 15/18 OK: dreamer_v3 trained 1F1B on a 2-stage x 2-data mesh within the compile budget")
PIPE

echo "=== stage 16/18: serving-fleet chaos drill (kill -9 + injected faults + poisoned rollout -> zero drops) (budget 900s) ==="
# ISSUE 17: a REAL 2-replica fleet (LocalFleet subprocesses behind the
# FleetRouter front) under concurrent session load takes injected
# serve.replica faults AND a SIGKILL mid-stream — zero dropped requests,
# every session completes, the killed replica respawns and is readmitted;
# then a poisoned (bit-flipped) newer commit must halt the rolling reload
# before ANY replica touches it, and a good commit must roll out to all.
timeout -k 15 900 python tests/fleet_drill.py

echo "=== stage 17/18: pod fault-tolerance drill (2-host fake DCN, SIGKILLed host -> collective restart -> full step count) (budget 900s) ==="
# ISSUE 19: a REAL 2-process pod (fake-DCN learner + actor cells, segments
# and params crossing the process boundary over the learner front) is
# supervised end to end: the actor "host" is SIGKILLed right after the
# first COMMIT — the pod's collective failure semantics tear every rank
# down (no rank trains past a dead peer), the PodSupervisor classifies
# the episode restartable and relaunches BOTH ranks with
# checkpoint.resume_from=auto, and the resumed pod completes the FULL
# step count from the newest shared commit, verifying clean for all ranks.
timeout -k 15 900 python tests/pod_drill.py

echo "=== stage 18/18: population drill (in-trace PBT beats fixed hyperparams at equal env steps) (budget 900s) ==="
# ISSUE 20: two seeded population=4 CartPole PPO runs — whole population
# vmapped inside ONE donated-carry fused executable (algo.max_recompiles=1)
# — with in-trace exploit/explore armed vs population.exploit_every=0 (the
# fixed-hyperparam control).  The PBT arm's best member must beat the
# control arm's worst member on final fitness; anything else means the
# selection machinery is dead weight.
timeout -k 15 900 python tests/population_drill.py

echo "CI gate: ALL GREEN"
