"""Fleet router semantics: sticky hashing under churn, least-loaded
dispatch, breaker eject/readmit, carry mirroring + migration, rolling
reload halt-on-poison — against lightweight stub replicas (no models), plus
the heavyweight pieces: the carry bit-identity pin on a real dreamer_v3
service, and one e2e chaos drill that kill -9s a real replica mid-stream.
"""

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from sheeprl_tpu.serve.fleet.router import FleetRouter, FleetServer, assign_replica


# -- stub replicas (router units run against these, not real models) ----------


class StubReplica:
    """A tiny HTTP server speaking just enough of the replica protocol."""

    def __init__(self, stateful: bool = False, step: int = 100):
        self.stateful = stateful
        self.step = step
        self.acts = 0
        self.resets = 0
        self.reloads = 0
        self.restores = 0
        self.fail_acts = 0  # answer 500 to the next N acts
        self.reload_mode = "ok"  # ok | stale (200, old step) | error (500)
        self.reload_to = None  # step taken on a successful reload
        self.carries = {}
        self.lock = threading.Lock()
        self._port = 0
        self._httpd = None
        self._thread = None
        self.open()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    def open(self) -> None:
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port), _stub_handler(self))
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _stub_handler(stub: StubReplica):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self):
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}") if length else {}

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._reply(
                    200,
                    {
                        "ok": True,
                        "algo": "stub",
                        "stateful": stub.stateful,
                        "checkpoint_step": stub.step,
                        "generation": 1,
                        "degraded": False,
                        "obs_spec": {"state": [[4], "float32"]},
                        "action_shape": [1],
                    },
                )
            else:
                self._reply(404, {"error": self.path})

        def do_POST(self):  # noqa: N802
            body = self._body()
            if self.path == "/v1/act":
                with stub.lock:
                    if stub.fail_acts > 0:
                        stub.fail_acts -= 1
                        self._reply(500, {"error": "stub induced act failure"})
                        return
                    stub.acts += 1
                    acts = stub.acts
                payload = {
                    "action": [0.5],
                    "shape": [1],
                    "dtype": "float32",
                    "generation": 1,
                    "checkpoint_step": stub.step,
                }
                session = body.get("session")
                if body.get("return_carry") and session is not None and stub.stateful:
                    payload["carry"] = {"session": session, "algo": "stub", "acts": acts}
                self._reply(200, payload)
            elif self.path == "/v1/reset":
                with stub.lock:
                    stub.resets += 1
                    stub.carries.pop(body.get("session"), None)
                self._reply(200, {"ok": True})
            elif self.path == "/v1/session_carry":
                with stub.lock:
                    stub.restores += 1
                    stub.carries[body["session"]] = body["snapshot"]
                self._reply(200, {"ok": True})
            elif self.path == "/v1/reload":
                with stub.lock:
                    stub.reloads += 1
                    if stub.reload_mode == "error":
                        self._reply(500, {"error": "stub reload failure"})
                        return
                    if stub.reload_mode == "ok" and stub.reload_to is not None:
                        stub.step = stub.reload_to
                    # "stale": 200, but the step never moves (the replica's
                    # own reload breaker kept old params)
                self._reply(
                    200,
                    {"reloaded": True, "generation": 2, "checkpoint_step": stub.step},
                )
            else:
                self._reply(404, {"error": self.path})

    return Handler


def _cfg(**fleet_overrides):
    fleet = {
        "health_poll_s": 0.05,
        "health_timeout_s": 2.0,
        "eject_threshold": 2,
        "readmit_s": 0.3,
        "route_retries": 3,
        "request_timeout_s": 10.0,
        "drain_timeout_s": 2.0,
        "reload_poll_s": 0.1,
        "carry_mirror": True,
    }
    fleet.update(fleet_overrides)
    return {"serve": {"fleet": fleet}}


@pytest.fixture
def stub_fleet():
    """Three probed stub replicas behind an (unstarted) router."""
    stubs = [StubReplica() for _ in range(3)]
    router = FleetRouter({f"r{i}": s.url for i, s in enumerate(stubs)}, _cfg())
    for rep in router.replica_list():
        assert router._probe(rep)
    yield router, stubs
    for s in stubs:
        s.close()


def _act(router, session=None):
    body = {"obs": {"state": [0.0, 0.0, 0.0, 0.0]}}
    if session is not None:
        body["session"] = session
    return router.act(json.dumps(body).encode())


# -- rendezvous hashing -------------------------------------------------------


def test_assign_replica_stable_under_churn():
    rids = ["r0", "r1", "r2"]
    sessions = [f"sess-{i}" for i in range(300)]
    before = {s: assign_replica(s, rids) for s in sessions}
    # every replica gets a non-degenerate share
    for rid in rids:
        share = sum(1 for v in before.values() if v == rid) / len(sessions)
        assert 0.15 < share < 0.55, (rid, share)
    # removing r1 moves ONLY r1's sessions
    after_removal = {s: assign_replica(s, ["r0", "r2"]) for s in sessions}
    for s in sessions:
        if before[s] != "r1":
            assert after_removal[s] == before[s]
    # adding r3 steals sessions only INTO r3
    after_add = {s: assign_replica(s, rids + ["r3"]) for s in sessions}
    for s in sessions:
        assert after_add[s] in (before[s], "r3")
    # deterministic and order-independent
    assert assign_replica("x", ["r2", "r0", "r1"]) == assign_replica("x", rids)
    assert assign_replica("x", []) is None


# -- dispatch -----------------------------------------------------------------


def test_least_loaded_tie_breaking(stub_fleet):
    router, _ = stub_fleet
    r0, r1, r2 = router.replica_list()
    r0.begin(), r0.begin(), r1.begin()  # load: r0=2 r1=1 r2=0
    assert router._pick(None, set()).rid == "r2"
    r2.begin()  # r1 and r2 tie at 1 — stable (lowest-rid) tie-break
    assert router._pick(None, set()).rid == "r1"
    # tried replicas are excluded even when least-loaded
    assert router._pick(None, {"r1"}).rid == "r2"
    assert router._pick(None, {"r0", "r1", "r2"}) is None


def test_sticky_sessions_survive_replica_death(stub_fleet):
    router, stubs = stub_fleet
    code, payload = _act(router, session="drill-session")
    assert code == 200
    home = payload["replica"]
    for _ in range(5):  # sticky while the home replica lives
        code, payload = _act(router, session="drill-session")
        assert code == 200 and payload["replica"] == home
    # kill the home replica: the session re-routes and sticks to a survivor
    stubs[int(home[1:])].close()
    router.mark_dead(home)
    code, payload = _act(router, session="drill-session")
    assert code == 200
    survivor = payload["replica"]
    assert survivor != home
    for _ in range(3):
        code, payload = _act(router, session="drill-session")
        assert code == 200 and payload["replica"] == survivor


def test_failover_costs_latency_not_requests(stub_fleet):
    """A replica answering 5xx is failed over transparently; only when every
    replica is unroutable does the client see the (retriable) 503."""
    router, stubs = stub_fleet
    stubs[0].fail_acts = 10
    stubs[1].fail_acts = 10
    for _ in range(4):  # every request lands despite two sick replicas
        code, payload = _act(router)
        assert code == 200
    assert router.stats()["failovers"] >= 1
    # all three dark -> 503 replica_unavailable (the client's retry signal)
    for stub in stubs:
        stub.close()
    for rep in router.replica_list():
        rep.probed = False
    code, payload = _act(router)
    assert code == 503 and "replica_unavailable" in payload["error"]
    assert router.stats()["unroutable"] == 1


# -- carry mirroring + migration ----------------------------------------------


def test_carry_mirror_and_migration_on_death():
    stubs = [StubReplica(stateful=True) for _ in range(2)]
    router = FleetRouter({f"r{i}": s.url for i, s in enumerate(stubs)}, _cfg())
    try:
        for rep in router.replica_list():
            assert router._probe(rep)
        assert router.stateful
        code, payload = _act(router, session="ep-1")
        assert code == 200
        # the piggybacked carry is mirrored router-side, stripped client-side
        assert "carry" not in payload
        home = payload["replica"]
        _act(router, session="ep-1")
        with router._sessions_lock:
            mirrored = router._sessions["ep-1"]["carry"]
        assert mirrored is not None and mirrored["acts"] >= 1

        # kill the home replica: the next act replays reset + carry restore
        # onto the survivor BEFORE forwarding the step
        stubs[int(home[1:])].close()
        router.mark_dead(home)
        code, payload = _act(router, session="ep-1")
        assert code == 200
        survivor_stub = stubs[int(payload["replica"][1:])]
        assert survivor_stub.resets == 1
        assert survivor_stub.restores == 1
        assert survivor_stub.carries["ep-1"] == mirrored
        assert router.stats()["migrations"] == 1
    finally:
        for s in stubs:
            s.close()


# -- breaker eject / readmit --------------------------------------------------


def test_breaker_eject_and_readmit():
    stubs = [StubReplica() for _ in range(2)]
    router = FleetRouter({f"r{i}": s.url for i, s in enumerate(stubs)}, _cfg())
    router.start()
    try:
        assert router.wait_healthy(min_replicas=2, timeout=10.0)
        stubs[0].close()  # r0 goes dark: probes fail, breaker opens
        deadline = time.monotonic() + 10.0
        r0 = router.get_replica("r0")
        while r0.routable and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not r0.routable
        assert router.stats()["ejects"] >= 1
        # traffic keeps flowing through r1 while r0 is ejected
        for _ in range(3):
            code, _ = _act(router)
            assert code == 200
        assert stubs[1].acts >= 3 and stubs[0].acts == 0

        stubs[0].open()  # back on the SAME port: half-open probe readmits
        deadline = time.monotonic() + 10.0
        while not router.get_replica("r0").routable and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.get_replica("r0").routable
        assert router.stats()["readmits"] >= 1
    finally:
        router.stop()
        for s in stubs:
            s.close()


# -- rolling reload -----------------------------------------------------------


def test_rolling_reload_halts_on_poison(tmp_path, stub_fleet):
    router, stubs = stub_fleet
    for s in stubs:
        s.reload_to = 200
    stubs[1].reload_mode = "error"  # r1 poisons the rollout
    with pytest.raises(IOError, match="r1 reload answered 500"):
        router._rollout_to(tmp_path / "step_200")
    # walk order is r0, r1, r2: the failure at r1 must leave r2 untouched
    assert stubs[0].reloads == 1
    assert stubs[1].reloads == 1
    assert stubs[2].reloads == 0
    assert all(not rep.draining for rep in router.replica_list())
    assert router.stats()["reload_halts"] == 1

    # a replica whose own breaker kept old params (200 but stale step) also halts
    stubs[1].reload_mode = "stale"
    with pytest.raises(IOError, match="r1 is at step"):
        router._rollout_to(tmp_path / "step_200")
    assert stubs[2].reloads == 0

    # healed: the rollout completes replica-by-replica
    stubs[1].reload_mode = "ok"
    assert router._rollout_to(tmp_path / "step_200") == 200
    assert [s.reloads for s in stubs] == [3, 3, 1]
    assert all(s.step == 200 for s in stubs)
    # cumulative per-replica successes: r0 alone on the two halted attempts,
    # all three on the healed one
    assert router.stats()["replicas_reloaded"] == 5


def test_watcher_rejects_poisoned_commit_before_any_replica(tmp_path):
    """A corrupted newer snapshot must be caught by the router's CRC verify
    (the CommitWatcher machinery) BEFORE any replica is asked to reload —
    old params keep serving everywhere."""
    from sheeprl_tpu.checkpoint.protocol import (
        shard_name,
        step_dir_name,
        write_commit,
        write_shard,
    )

    stubs = [StubReplica(step=100) for _ in range(2)]
    # reload_poll_s is huge so the background watcher thread never races the
    # manual reload_once() calls below — the poll is driven by hand
    router = FleetRouter(
        {f"r{i}": s.url for i, s in enumerate(stubs)},
        _cfg(reload_poll_s=3600.0),
        ckpt_root=tmp_path,
    )
    router.start()
    try:
        assert router.wait_healthy(min_replicas=2, timeout=10.0)
        assert router._fleet_store.step == 100

        # commit step_200, then flip bytes in its shard (bit rot post-commit)
        poisoned = tmp_path / step_dir_name(200)
        poisoned.mkdir()
        write_shard(poisoned, 0, {"agent": {"x": np.zeros(64)}})
        assert write_commit(poisoned, 200, world=1, timeout_s=30.0)
        shard = poisoned / shard_name(0)
        raw = bytearray(shard.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        shard.write_bytes(bytes(raw))

        code, payload = router.reload_once()
        assert code == 200 and payload["reloaded"] is False
        assert all(s.reloads == 0 for s in stubs), "poison reached a replica"
        assert router._fleet_store.step == 100
        assert router.watcher.last_error is not None

        # a GOOD newer commit still rolls out after the poison
        for s in stubs:
            s.reload_to = 300
        good = tmp_path / step_dir_name(300)
        good.mkdir()
        write_shard(good, 0, {"agent": {"x": np.ones(64)}})
        assert write_commit(good, 300, world=1, timeout_s=30.0)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and router._fleet_store.step != 300:
            router.reload_once()
            time.sleep(0.2)  # breaker cool-down after the poison
        assert router._fleet_store.step == 300
        assert all(s.step == 300 for s in stubs)
        assert router.stats()["rolling_reloads"] == 1
    finally:
        router.stop()
        for s in stubs:
            s.close()


# -- carry snapshot bit-identity (real dreamer_v3 service) --------------------


@pytest.mark.slow
def test_session_carry_restore_bit_identity(dv3_ckpt):
    """The migration primitive's contract: a restored carry produces a
    bit-identical next action to the uninterrupted session (same params,
    same seed counter — the only allowed divergence source is the carry,
    and there must be none)."""
    from sheeprl_tpu.serve import PolicyService

    svc = PolicyService.from_checkpoint(
        dv3_ckpt,
        ["serve.batch_ladder=[1,4]", "serve.max_wait_ms=2", "serve.watch_commits=False"],
    )
    svc.start()
    try:
        assert svc.player.stateful
        obs = {
            k: np.zeros(shape, np.dtype(dt))
            for k, (shape, dt) in svc.player.obs_spec.items()
        }
        svc.act(obs, session="orig", timeout=120.0)
        snap = svc.get_session_carry("orig")
        assert snap is not None and "crc" in snap

        # uninterrupted continuation, with the service's seed counter pinned
        # (dreamer's posterior sample draws from PRNGKey(seed) even when
        # greedy, so bit-identity requires identical seeds too)
        with svc._seed_lock:
            svc._seed = 424242
        a_uninterrupted = svc.act(obs, greedy=True, session="orig", timeout=120.0)

        # "migrated" continuation: restore the snapshot under a fresh
        # session id — exactly what the router replays onto a survivor
        svc.restore_session_carry("migrated", snap)
        with svc._seed_lock:
            svc._seed = 424242
        a_migrated = svc.act(obs, greedy=True, session="migrated", timeout=120.0)

        np.testing.assert_array_equal(a_uninterrupted, a_migrated)

        # tampering is detected: a flipped byte in a leaf fails the CRC
        import copy

        torn = copy.deepcopy(snap)
        blob = torn["carry"][0]["__nd__"]
        import base64

        raw = bytearray(base64.b64decode(blob["b64"]))
        raw[0] ^= 0xFF
        blob["b64"] = base64.b64encode(bytes(raw)).decode("ascii")
        with pytest.raises(ValueError, match="CRC"):
            svc.restore_session_carry("torn", torn)
        # wrong leaf count is rejected before the CRC even runs
        with pytest.raises(ValueError, match="leaves"):
            svc.restore_session_carry("short", {**snap, "carry": snap["carry"][:1]})
        # unknown sessions and stateless players answer None, not garbage
        assert svc.get_session_carry("never-seen") is None
    finally:
        svc.stop()


# -- e2e chaos drill: kill -9 a real replica mid-stream -----------------------


@pytest.mark.slow
def test_fleet_kill_drill_zero_drops(ppo_ckpt):
    """16 concurrent session-bearing clients stream acts through the fleet
    front while one replica is SIGKILLed mid-stream: zero dropped requests,
    every session completes, and /metrics shows the failover."""
    import urllib.request

    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.fleet.replicas import LocalFleet

    fleet = LocalFleet(
        str(ppo_ckpt),
        overrides=["serve.batch_ladder=[1,8]", "serve.max_wait_ms=2"],
        replicas=2,
        backoff_base_s=0.2,
        backoff_max_s=1.0,
        echo=False,
    )
    fleet.start()
    server = None
    try:
        router = FleetRouter(fleet.addresses(), _cfg(request_timeout_s=60.0))
        fleet.attach(router)
        server = FleetServer(router)
        server.start()
        assert router.wait_healthy(min_replicas=2, timeout=120.0)

        health = PolicyClient(server.url, timeout=120.0).health()
        obs = {
            k: np.zeros(shape, np.dtype(dt))
            for k, (shape, dt) in health["obs_spec"].items()
        }
        action_shape = tuple(health["action_shape"])

        n_clients, n_requests = 16, 30
        errors, done = [], []
        barrier = threading.Barrier(n_clients + 1)

        def client_thread(cid: int):
            client = PolicyClient(server.url, timeout=120.0, retries=6, retry_base_s=0.2)
            session = f"drill-{cid}"
            barrier.wait(timeout=120.0)
            try:
                for _ in range(n_requests):
                    a = client.act(obs, greedy=True, session=session)
                    assert a.shape == action_shape
                    time.sleep(0.05)  # pace the stream so the kill lands mid-flight
                done.append(cid)
            except Exception as e:  # noqa: BLE001 — the gate IS "no exception"
                errors.append((cid, repr(e)))

        threads = [threading.Thread(target=client_thread, args=(i,)) for i in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait(timeout=120.0)
        time.sleep(0.4)  # let requests hit both replicas mid-stream
        fleet.kill(0, sig=signal.SIGKILL)
        for t in threads:
            t.join(300.0)

        assert not errors, errors
        assert sorted(done) == list(range(n_clients)), "a session failed to complete"
        stats = router.stats()
        # >= because a client whose response was torn mid-read retries a
        # request the router already counted as routed
        assert stats["routed"] >= n_clients * n_requests

        with urllib.request.urlopen(server.url + "/metrics", timeout=30) as resp:
            body = resp.read().decode()
        assert "sheeprl_fleet_replicas" in body, body[:400]
        # the kill must be visible: a failover, an eject, or the respawn
        visible = any(
            f"sheeprl_fleet_{name}" in body
            and _metric_value(body, f"sheeprl_fleet_{name}") > 0
            for name in ("failovers", "ejects", "respawns")
        )
        assert visible, body[:1000]
    finally:
        if server is not None:
            server.stop()
        fleet.stop()


def _metric_value(prometheus_body: str, name: str) -> float:
    for line in prometheus_body.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[-1])
    return 0.0
