"""Shared fixtures: tiny committed dryrun checkpoints to serve from.

Session-scoped — training even a tiny agent dominates the module's wall
clock, so every test in the package reuses the same snapshot.
"""

import pytest

from sheeprl_tpu.cli import run
from tests.ckpt_utils import find_checkpoints


def _train_tiny(algo: str, env_id: str, log_dir: str, extra=()) -> str:
    run(
        [
            f"exp={algo}",
            "env=dummy",
            f"env.id={env_id}",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "fabric.accelerator=cpu",
            "metric.log_level=0",
            "checkpoint.every=1",
            "buffer.memmap=False",
            "algo.learning_starts=0",
            f"log_dir={log_dir}",
            "print_config=False",
            "algo.run_test=False",
            *extra,
        ]
    )
    ckpts = find_checkpoints(log_dir)
    assert ckpts, f"dryrun produced no committed checkpoint under {log_dir}"
    return ckpts[-1]


@pytest.fixture(scope="session")
def sac_ckpt(tmp_path_factory):
    log_dir = tmp_path_factory.mktemp("serve_sac")
    return _train_tiny("sac", "continuous_dummy", str(log_dir))


@pytest.fixture(scope="session")
def ppo_ckpt(tmp_path_factory):
    log_dir = tmp_path_factory.mktemp("serve_ppo")
    return _train_tiny("ppo", "discrete_dummy", str(log_dir))


DV3_TINY = (
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
)


@pytest.fixture(scope="session")
def dv3_ckpt(tmp_path_factory):
    log_dir = tmp_path_factory.mktemp("serve_dv3")
    return _train_tiny("dreamer_v3", "discrete_dummy", str(log_dir), DV3_TINY)
