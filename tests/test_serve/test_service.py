"""PolicyService + HTTP surface E2E over real committed dryrun checkpoints.

The heavyweight fixtures (tiny trained agents) are session-scoped in
conftest.py; everything here serves from them.
"""

import threading

import numpy as np
import pytest

from sheeprl_tpu.config.compose import ConfigError
from sheeprl_tpu.serve import PolicyService
from sheeprl_tpu.serve.loader import resolve_checkpoint
from sheeprl_tpu.utils.profiler import COMPILE_MONITOR


def _zero_obs(player):
    return {k: np.zeros(shape, np.dtype(dt)) for k, (shape, dt) in player.obs_spec.items()}


# -- loader: discovery spellings ---------------------------------------------


def test_resolve_checkpoint_spellings(ppo_ckpt, tmp_path):
    import pathlib

    step_dir = pathlib.Path(ppo_ckpt)
    assert resolve_checkpoint(step_dir) == step_dir
    # checkpoint root → newest committed snapshot
    assert resolve_checkpoint(step_dir.parent) == step_dir
    # version dir and run dir → same
    assert resolve_checkpoint(step_dir.parent.parent) == step_dir
    assert resolve_checkpoint(step_dir.parent.parent.parent) == step_dir
    with pytest.raises(ConfigError):
        resolve_checkpoint(tmp_path / "nope")


def test_resolve_checkpoint_rejects_torn_snapshot(ppo_ckpt, tmp_path):
    import os
    import pathlib

    from sheeprl_tpu.checkpoint.protocol import step_dir_name, write_shard

    torn = tmp_path / step_dir_name(999)
    os.makedirs(torn)
    write_shard(torn, 0, {"agent": {}})
    with pytest.raises(ConfigError, match="torn|COMMIT"):
        resolve_checkpoint(torn)
    # a root holding ONLY a torn snapshot has no servable checkpoint
    with pytest.raises(ConfigError, match="no committed checkpoint"):
        resolve_checkpoint(pathlib.Path(tmp_path))


# -- service -----------------------------------------------------------------


@pytest.fixture(scope="module")
def ppo_service(ppo_ckpt):
    svc = PolicyService.from_checkpoint(
        ppo_ckpt, ["serve.max_wait_ms=2", "serve.watch_commits=False"]
    )
    svc.start()
    yield svc
    svc.stop()


def test_service_single_and_concurrent_requests(ppo_service):
    obs = _zero_obs(ppo_service.player)
    a = ppo_service.act(obs, timeout=60.0)
    assert a.shape == ppo_service.player.action_shape
    # concurrent burst: every caller gets exactly one row back, none dropped
    results, errors = [], []

    def caller(i):
        try:
            results.append(ppo_service.act(obs, greedy=(i % 2 == 0), timeout=60.0))
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)
    assert not errors
    assert len(results) == 24
    stats = ppo_service.stats()
    assert stats["errors"] == 0
    assert stats["served"] >= 25


def test_steady_state_never_recompiles(ppo_service):
    """The acceptance gate: after warm-up, Compile/* counters stay flat no
    matter how ragged the arrival pattern is (padding hits warmed rungs)."""
    obs = _zero_obs(ppo_service.player)
    ppo_service.act(obs, timeout=60.0)  # ensure fully settled
    before, _ = COMPILE_MONITOR.totals()
    for burst in (1, 3, 7, 12, 30):  # pads to rungs 1/8/8/32/32
        threads = [
            threading.Thread(target=ppo_service.act, args=(obs,), kwargs={"timeout": 60.0})
            for _ in range(burst)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
    after, _ = COMPILE_MONITOR.totals()
    assert after == before, f"steady-state serving recompiled: {after - before} new executables"


def test_service_stats_shape(ppo_service):
    stats = ppo_service.stats()
    for field in (
        "served", "batches", "errors", "avg_batch", "padded_frac",
        "generation", "checkpoint_step", "batch_ladder",
        "compile_executables", "p50_ms", "p99_ms",
    ):
        assert field in stats
    assert stats["checkpoint_step"] > 0
    assert np.isfinite(stats["p50_ms"])


# -- HTTP surface ------------------------------------------------------------


def test_http_round_trip(ppo_service):
    from sheeprl_tpu.serve.client import PolicyClient, ServerError
    from sheeprl_tpu.serve.server import PolicyServer

    server = PolicyServer(ppo_service)
    # service is already started (module fixture); bring up just the socket
    server._thread = threading.Thread(target=server._httpd.serve_forever, daemon=True)
    server._thread.start()
    try:
        client = PolicyClient(server.url)
        health = client.health()
        assert health["ok"] and health["algo"] == "ppo"

        obs = _zero_obs(ppo_service.player)
        a = client.act(obs, greedy=True)
        assert a.shape == ppo_service.player.action_shape

        packed = PolicyClient(server.url, packed=True)
        a2 = packed.act(obs, greedy=True)
        np.testing.assert_array_equal(a, a2)  # same greedy action, both codecs

        client.reset("some-session")
        stats = client.stats()
        assert stats["served"] >= 2

        with pytest.raises(ServerError) as exc:
            client._call("POST", "/v1/act", {"obs": {}})  # missing keys
        assert exc.value.status == 400
        with pytest.raises(ServerError) as exc:
            client._call("GET", "/nope")
        assert exc.value.status == 404
    finally:
        server._httpd.shutdown()
        server._httpd.server_close()


# -- evaluation CLI rides the same path --------------------------------------


def test_evaluation_cli_through_loader(ppo_ckpt):
    """cli:evaluation resolves + rebuilds through serve.loader, including the
    run-dir spelling the server accepts (not just an explicit file)."""
    import pathlib

    from sheeprl_tpu.cli import evaluation

    run_dir = pathlib.Path(ppo_ckpt).parent.parent
    evaluation([f"checkpoint_path={run_dir}", "env.capture_video=False"])


# -- dreamer_v3: stateful sessions (slow: XS world model still compiles) -----


@pytest.mark.slow
def test_dreamer_v3_sessions(dv3_ckpt):
    svc = PolicyService.from_checkpoint(
        dv3_ckpt,
        ["serve.batch_ladder=[1,8]", "serve.max_wait_ms=2", "serve.watch_commits=False"],
    )
    svc.start()
    try:
        assert svc.player.stateful
        obs = _zero_obs(svc.player)
        a1 = svc.act(obs, session="ep-1", timeout=120.0)
        assert svc.stats()["sessions"] == 1
        a2 = svc.act(obs, session="ep-1", timeout=120.0)
        assert a1.shape == a2.shape == svc.player.action_shape
        svc.reset_session("ep-1")
        assert svc.stats()["sessions"] == 0
        # sessionless requests run from a zero carry and leak no state
        svc.act(obs, timeout=120.0)
        assert svc.stats()["sessions"] == 0
        assert svc.stats()["errors"] == 0
    finally:
        svc.stop()
