"""Unit tests: pad-to-ladder selection + admission-queue fairness and
backpressure (no jax, no training — pure host logic)."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.batcher import (
    AdmissionQueue,
    LatencyTracker,
    QueueFull,
    ServiceStopped,
    _Request,
    pick_ladder_size,
)

LADDER = (1, 8, 32, 128)


def _req(i: int = 0) -> _Request:
    return _Request({"x": np.asarray([i])}, greedy=True, session=None)


# -- pad-to-ladder -----------------------------------------------------------


def test_pick_ladder_size_exact_and_padded():
    assert pick_ladder_size(1, LADDER) == 1
    assert pick_ladder_size(2, LADDER) == 8
    assert pick_ladder_size(8, LADDER) == 8
    assert pick_ladder_size(9, LADDER) == 32
    assert pick_ladder_size(128, LADDER) == 128


def test_pick_ladder_size_unsorted_ladder():
    assert pick_ladder_size(5, (128, 1, 32, 8)) == 8


def test_pick_ladder_size_rejects_overflow_and_empty():
    with pytest.raises(ValueError):
        pick_ladder_size(129, LADDER)  # above the top rung: never recompile
    with pytest.raises(ValueError):
        pick_ladder_size(0, LADDER)


# -- admission queue: fairness -----------------------------------------------


def test_queue_strict_fifo_order():
    q = AdmissionQueue(max_pending=64)
    reqs = [_req(i) for i in range(10)]
    for r in reqs:
        q.put(r)
    batch = q.get_batch(max_batch=10, max_wait_s=0.0)
    assert batch == reqs  # arrival order, nobody reordered/starved


def test_queue_coalesces_up_to_max_batch():
    q = AdmissionQueue(max_pending=64)
    for i in range(12):
        q.put(_req(i))
    first = q.get_batch(max_batch=8, max_wait_s=0.0)
    second = q.get_batch(max_batch=8, max_wait_s=0.0)
    assert len(first) == 8 and len(second) == 4


def test_queue_max_wait_anchored_to_oldest():
    """The dispatch clock starts at the OLDEST request's arrival — a slow
    trickle of later arrivals cannot hold the head request hostage."""
    q = AdmissionQueue(max_pending=64)
    q.put(_req(0))
    t0 = time.perf_counter()
    batch = q.get_batch(max_batch=8, max_wait_s=0.15)
    waited = time.perf_counter() - t0
    assert len(batch) == 1
    assert waited < 1.0  # returned at ~max_wait, not blocked indefinitely


def test_queue_dispatches_immediately_when_full_batch_waiting():
    q = AdmissionQueue(max_pending=64)
    for i in range(8):
        q.put(_req(i))
    t0 = time.perf_counter()
    batch = q.get_batch(max_batch=8, max_wait_s=5.0)
    assert len(batch) == 8
    assert time.perf_counter() - t0 < 1.0  # did NOT wait out max_wait


# -- admission queue: backpressure -------------------------------------------


def test_queue_backpressure_nonblocking():
    q = AdmissionQueue(max_pending=2)
    q.put(_req(0))
    q.put(_req(1))
    with pytest.raises(QueueFull):
        q.put(_req(2), block=False)


def test_queue_backpressure_blocking_timeout():
    q = AdmissionQueue(max_pending=1)
    q.put(_req(0))
    t0 = time.perf_counter()
    with pytest.raises(QueueFull):
        q.put(_req(1), block=True, timeout=0.1)
    assert time.perf_counter() - t0 >= 0.1


def test_queue_blocked_put_unblocks_on_pop():
    q = AdmissionQueue(max_pending=1)
    q.put(_req(0))
    ok = threading.Event()

    def producer():
        q.put(_req(1), block=True, timeout=5.0)
        ok.set()

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.05)
    q.get_batch(max_batch=1, max_wait_s=0.0)  # frees a slot
    t.join(5.0)
    assert ok.is_set()


def test_queue_close_rejects_and_returns_pending():
    q = AdmissionQueue(max_pending=8)
    r0, r1 = _req(0), _req(1)
    q.put(r0)
    q.put(r1)
    pending = q.close()
    assert pending == [r0, r1]
    with pytest.raises(ServiceStopped):
        q.put(_req(2))
    assert q.get_batch(max_batch=8, max_wait_s=0.0) == []


# -- request handle / latency ------------------------------------------------


def test_request_resolve_and_fail():
    r = _req()
    r.resolve(np.asarray([1.0]))
    assert r.wait(1.0) == np.asarray([1.0])
    r2 = _req()
    r2.fail(RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        r2.wait(1.0)


def test_latency_tracker_percentiles():
    lt = LatencyTracker(window=128)
    for ms in range(1, 101):
        lt.record(ms / 1e3)
    p = lt.percentiles((50, 99))
    assert 45 <= p["p50_ms"] <= 55
    assert 95 <= p["p99_ms"] <= 100


def test_request_timeout_marks_cancelled():
    """A wait() timeout (the HTTP 504 path) flags the still-queued request so
    the dispatcher drops it instead of spending a batch slot — and, for
    stateful sessions, advancing the latent chain on an observation the
    client will resend."""
    r = _req()
    with pytest.raises(TimeoutError):
        r.wait(0.01)
    assert r.cancelled
    done = _req()
    done.resolve(np.asarray([1.0]))
    done.wait(1.0)
    assert not done.cancelled


# -- same-session coalescing -------------------------------------------------


def test_session_waves_chain_duplicate_sessions():
    """Two pipelined requests for one stateful session must not share a
    batch (both would read the same pre-batch carry); sessionless rows pack
    into the first wave."""
    from sheeprl_tpu.serve.service import _session_waves

    def req(session):
        return _Request({"x": np.zeros(1)}, greedy=True, session=session)

    a1, b1, n1, a2, n2, a3 = (
        req("a"), req("b"), req(None), req("a"), req(None), req("a")
    )
    waves = _session_waves([a1, b1, n1, a2, n2, a3])
    assert waves == [[a1, b1, n1, n2], [a2], [a3]]  # per-session order kept
    # no duplicates inside any wave
    for wave in waves:
        ids = [r.session for r in wave if r.session is not None]
        assert len(ids) == len(set(ids))
    # all-sessionless (and stateless players skip splitting entirely)
    assert _session_waves([n1, n2]) == [[n1, n2]]
