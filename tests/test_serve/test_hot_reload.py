"""The hot-reload drill from the acceptance criteria: serve a committed
dryrun checkpoint, stream requests continuously, commit a NEWER snapshot
mid-stream, and assert (a) zero dropped/errored requests and (b) post-reload
actions come from the new params.

The new snapshot's actor is forged so its greedy action is unmistakable:
mean-head kernel zeroed, bias +100 → tanh(100) = 1.0 exactly on every
action dim, which the trained tiny actor never produces on a zero obs.
"""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.protocol import (
    checkpoint_step,
    step_dir_name,
    write_commit,
    write_shard,
)
from sheeprl_tpu.serve import PolicyService
from sheeprl_tpu.utils.profiler import COMPILE_MONITOR


def _forge_saturated_actor(state):
    """Copy of the checkpoint state whose actor mean head outputs +100."""
    import copy

    new_state = copy.deepcopy(state)

    def patch(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "mean" and isinstance(v, dict) and "bias" in v:
                    v["kernel"] = np.zeros_like(np.asarray(v["kernel"]))
                    v["bias"] = np.full_like(np.asarray(v["bias"]), 100.0)
                else:
                    patch(v)

    patch(new_state["agent"]["actor"])
    return new_state


def test_hot_reload_mid_stream(sac_ckpt):
    svc = PolicyService.from_checkpoint(
        sac_ckpt,
        [
            "serve.max_wait_ms=2",
            "serve.reload_poll_s=0.1",
            "serve.batch_ladder=[1,8,32]",
        ],
    )
    assert svc.watcher is not None, "serving a run dir must arm the commit watcher"
    svc.start()
    try:
        obs = {
            k: np.zeros(shape, np.dtype(dt))
            for k, (shape, dt) in svc.player.obs_spec.items()
        }
        # old params: tiny trained actor, greedy action nowhere near the bound
        a_old = svc.act(obs, greedy=True, timeout=60.0)
        assert np.all(np.abs(a_old) < 0.9)
        exe_before, _ = COMPILE_MONITOR.totals()

        # continuous request stream across the swap
        errors, actions, stop = [], [], threading.Event()

        def stream(wid: int):
            while not stop.is_set():
                try:
                    actions.append(svc.act(obs, greedy=True, timeout=60.0))
                except Exception as e:
                    errors.append(e)

        threads = [threading.Thread(target=stream, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # in-flight traffic before the commit

        # commit a NEWER snapshot into the same run's checkpoint root
        old_state = svc.fabric.load(sac_ckpt)
        new_state = _forge_saturated_actor(old_state)
        new_step = checkpoint_step(sac_ckpt) + 100
        step_dir = svc.ckpt_root / step_dir_name(new_step)
        step_dir.mkdir()
        write_shard(step_dir, 0, new_state)
        assert write_commit(step_dir, new_step, world=1, timeout_s=30.0)

        # the watcher must pick it up without the stream stopping
        deadline = time.monotonic() + 60.0
        while svc.store.generation == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert svc.store.generation == 1, f"no hot reload (last_error={svc.watcher.last_error})"
        assert svc.store.step == new_step

        time.sleep(0.3)  # post-swap traffic
        stop.set()
        for t in threads:
            t.join(60.0)

        # (a) zero dropped/errored requests across the swap
        assert not errors
        assert svc.stats()["errors"] == 0
        assert len(actions) > 0

        # (b) post-reload actions come from the NEW params: saturated bound
        a_new = svc.act(obs, greedy=True, timeout=60.0)
        np.testing.assert_allclose(a_new, np.ones_like(a_new), atol=1e-3)

        # and the swap compiled nothing: same shapes, same executables
        exe_after, _ = COMPILE_MONITOR.totals()
        assert exe_after == exe_before

        # the stream must contain BOTH regimes (old actions, then saturated)
        saturated = [a for a in actions if np.all(np.abs(a - 1.0) < 1e-3)]
        unsaturated = [a for a in actions if np.all(np.abs(a) < 0.9)]
        assert saturated, "no post-reload action observed in the stream"
        assert unsaturated, "no pre-reload action observed in the stream"
        assert svc.watcher.reloads == 1
    finally:
        svc.stop()
