"""Hot-reload unit tests: double-buffered param store + COMMIT watcher
(forged snapshot dirs, injected loaders — no jax, no training)."""

import os

import pytest

from sheeprl_tpu.checkpoint.protocol import (
    step_dir_name,
    write_commit,
    write_shard,
)
from sheeprl_tpu.serve.reload import CommitWatcher, ParamStore


def _commit(root, step: int, payload) -> str:
    d = root / step_dir_name(step)
    os.makedirs(d, exist_ok=True)
    write_shard(d, 0, payload)
    assert write_commit(d, step, world=1, timeout_s=5.0)
    return str(d)


# -- ParamStore --------------------------------------------------------------


def test_param_store_swap_bumps_generation():
    store = ParamStore({"w": 1}, step=10)
    assert (store.get(), store.generation, store.step) == ({"w": 1}, 0, 10)
    gen = store.swap({"w": 2}, step=20)
    assert gen == 1
    assert (store.get(), store.generation, store.step) == ({"w": 2}, 1, 20)


def test_param_store_double_buffering():
    """A reader holding the OLD tree keeps it alive across a swap — the swap
    only redirects the pointer for FUTURE snapshots."""
    old = {"w": [1.0]}
    store = ParamStore(old, step=1)
    held, gen_at_dispatch, _ = store.snapshot()  # an in-flight batch
    store.swap({"w": [2.0]}, step=2)
    assert held is old and held["w"] == [1.0]  # untouched, still serving
    assert gen_at_dispatch == 0
    assert store.snapshot()[0]["w"] == [2.0]  # next batch gets the new tree


# -- CommitWatcher -----------------------------------------------------------


def test_watcher_swaps_on_newer_commit(tmp_path):
    _commit(tmp_path, 10, {"v": 10})
    store = ParamStore("old", step=10)
    loaded = []

    def load(step_dir):
        loaded.append(str(step_dir))
        return f"params@{os.path.basename(step_dir)}"

    w = CommitWatcher(tmp_path, store, load, poll_s=60.0)
    assert w.poll_once() is None  # nothing newer than step 10
    _commit(tmp_path, 20, {"v": 20})
    gen = w.poll_once()
    assert gen == 1 and w.reloads == 1
    assert store.step == 20 and store.get() == f"params@{step_dir_name(20)}"
    assert loaded == [str(tmp_path / step_dir_name(20))]


def test_watcher_ignores_uncommitted_snapshot(tmp_path):
    _commit(tmp_path, 10, {"v": 10})
    store = ParamStore("old", step=10)
    w = CommitWatcher(tmp_path, store, lambda d: "new", poll_s=60.0)
    # torn snapshot: shard written, COMMIT never lands
    torn = tmp_path / step_dir_name(20)
    os.makedirs(torn)
    write_shard(torn, 0, {"v": 20})
    assert w.poll_once() is None
    assert store.get() == "old" and store.step == 10


def test_watcher_keeps_serving_on_load_error(tmp_path):
    _commit(tmp_path, 10, {"v": 10})
    store = ParamStore("old", step=10)

    def bad_load(step_dir):
        raise OSError("torn read")

    w = CommitWatcher(tmp_path, store, bad_load, poll_s=60.0)
    _commit(tmp_path, 20, {"v": 20})
    assert w.poll_once() is None  # swallowed, old params keep serving
    assert store.get() == "old" and store.generation == 0
    assert "torn read" in w.last_error


def test_watcher_background_thread(tmp_path):
    from sheeprl_tpu.checkpoint import wait_for_commit

    _commit(tmp_path, 10, {"v": 10})
    store = ParamStore("old", step=10)
    w = CommitWatcher(tmp_path, store, lambda d: "new", poll_s=0.05)
    w.start()
    try:
        _commit(tmp_path, 30, {"v": 30})
        assert wait_for_commit(tmp_path, 10, timeout_s=5.0) is not None
        deadline = 50
        while store.generation == 0 and deadline:
            deadline -= 1
            import time

            time.sleep(0.1)
        assert store.generation == 1 and store.step == 30
    finally:
        w.stop()


def test_wait_for_commit_times_out(tmp_path):
    from sheeprl_tpu.checkpoint import wait_for_commit

    assert wait_for_commit(tmp_path, 0, timeout_s=0.2, poll_s=0.05) is None
