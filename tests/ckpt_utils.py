"""Shared helper for locating checkpoints written by the fault-tolerant
checkpointing subsystem (committed ``step_*`` snapshot directories) with a
fallback to the legacy flat ``ckpt_*.ckpt`` layout."""

import glob


def find_checkpoints(root):
    """All COMMITTED snapshot directories (plus any legacy flat-file
    checkpoints) under ``root``, oldest → newest."""
    from sheeprl_tpu.checkpoint import list_checkpoints

    out = []
    for ckpt_root in glob.glob(f"{root}/**/checkpoint", recursive=True):
        out.extend(str(p) for p in list_checkpoints(ckpt_root))
    out.extend(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True))
    import os

    return sorted(out, key=os.path.getmtime)
