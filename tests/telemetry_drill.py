#!/usr/bin/env python
"""run_ci stage 12: live-introspection + postmortem drill.

Launches a short dreamer_v3 training run as a SUBPROCESS with
``telemetry.introspect.port=0`` armed and a seeded ``env.step`` raise
planted mid-run (``SHEEPRL_FAULT_PLAN``), then — while the run is alive —

1. parses the printed introspection URL off the child's stdout,
2. scrapes ``/metrics`` until the Prometheus exposition carries the
   compile counters (content type + text format asserted),
3. scrapes ``/v1/phase`` and checks the breakdown's fractions sum to ~1.0,

waits for the injected fault to kill the run (nonzero exit), and asserts
the run directory holds a well-formed ``postmortem.json`` whose event ring
contains the injected fault — the "every chaos path leaves evidence"
contract, exercised across a real process boundary.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

LOG_DIR = "/tmp/run_ci_telemetry"

# raises at env.step invocation 40: comfortably after warm-up/compiles
# (scrape material exists) and comfortably inside the step budget below
FAULT_PLAN = json.dumps(
    {"seed": 3, "plan": [{"site": "env.step", "kind": "raise", "at": 40}]}
)

RUN_ARGS = [
    "exp=dreamer_v3",
    "algo=dreamer_v3_XS",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    # the dreamer exps arm RestartOnException (PR 8 chaos hardening), which
    # would absorb the planted raise — this drill needs the fault FATAL so
    # the crash path (postmortem dump + final flush) is what gets exercised
    "env.restart_on_exception=False",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
    "algo.learning_starts=8",
    "algo.total_steps=4096",  # the fault ends the run, not the budget
    "algo.replay_ratio=0.1",
    "algo.run_test=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "metric.log_level=1",
    "metric.log_every=1",
    "telemetry.introspect.port=0",
    f"log_dir={LOG_DIR}",
    "print_config=False",
]


def fetch(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read().decode()


def main() -> int:
    import shutil

    shutil.rmtree(LOG_DIR, ignore_errors=True)
    env = {
        **os.environ,
        "SHEEPRL_FAULT_PLAN": FAULT_PLAN,
        "JAX_PLATFORMS": "cpu",
    }
    child = subprocess.Popen(
        [sys.executable, "-m", "sheeprl_tpu", *RUN_ARGS],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )

    # drain stdout on a thread (the child must never block on a full pipe)
    lines: list = []
    url_found = threading.Event()

    def drain() -> None:
        for line in child.stdout:  # type: ignore[union-attr]
            lines.append(line)
            if "telemetry introspection on" in line:
                url_found.set()

    reader = threading.Thread(target=drain, daemon=True)
    reader.start()

    try:
        if not url_found.wait(timeout=180):
            raise AssertionError("child never printed the introspection URL")
        m = re.search(
            r"telemetry introspection on (http://\S+)", "".join(lines)
        )
        assert m, "URL line present but unparseable"
        url = m.group(1)
        print(f"[drill] scraping {url}")

        # /healthz answers immediately; /metrics carries the compile
        # counters once warm-up compiles have been recorded — poll for them
        status, _, body = fetch(url + "/healthz")
        assert status == 200 and json.loads(body)["ok"] is True

        deadline = time.monotonic() + 300
        ctype = metrics_body = None
        while time.monotonic() < deadline:
            if child.poll() is not None:
                raise AssertionError(
                    "child exited before /metrics showed compile counters:\n"
                    + "".join(lines[-30:])
                )
            status, ctype, metrics_body = fetch(url + "/metrics")
            assert status == 200
            if "sheeprl_compile_executables" in metrics_body:
                break
            time.sleep(2.0)
        assert metrics_body and "sheeprl_compile_executables" in metrics_body, (
            "compile counters never appeared in /metrics"
        )
        assert ctype == "text/plain; version=0.0.4; charset=utf-8", ctype
        assert re.search(
            r"^# TYPE sheeprl_compile_executables gauge$", metrics_body, re.M
        ), "Prometheus TYPE line missing"
        print("[drill] /metrics OK (content type + exposition format)")

        # poll /v1/phase until a phase span has closed (the first training
        # iteration opens rollout/update.dispatch via the timer bridge) —
        # the planted fault only fires mid-training, so one must appear
        phase = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and child.poll() is None:
            status, _, body = fetch(url + "/v1/phase")
            assert status == 200
            phase = json.loads(body)
            if phase["phases"]:
                break
            time.sleep(2.0)
        assert phase is not None and phase["phases"], (
            "no phase span ever closed before the run died"
        )
        total = sum(p["frac"] for p in phase["phases"].values()) + phase["other_frac"]
        assert abs(total - 1.0) < 1e-3, f"phase fractions sum to {total}"
        print(f"[drill] /v1/phase OK (phases: {sorted(phase['phases'])}, Σfrac={total:.4f})")

        # now let the planted fault kill the run
        rc = child.wait(timeout=600)
        assert rc != 0, "the injected env.step fault should have killed the run"
        print(f"[drill] child died as planned (rc={rc})")
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)

    # the postmortem: well-formed, right reason, fault event in the ring
    pm_files = glob.glob(f"{LOG_DIR}/**/postmortem.json", recursive=True)
    assert pm_files, "crashed run left no postmortem.json\n" + "".join(lines[-30:])
    doc = json.load(open(pm_files[0]))
    assert doc["schema"] == "sheeprl.postmortem/1"
    assert doc["reason"] == "exception"
    kinds = [e["kind"] for e in doc["events"]]
    injected = [e for e in doc["events"] if e["kind"] == "fault.injected"]
    assert injected and injected[0]["site"] == "env.step", kinds
    assert any(e["kind"] == "crash" for e in doc["events"])
    assert doc["monitors"]["resilience"]["injected"] >= 1
    print(
        f"[drill] postmortem OK: {pm_files[0]} "
        f"({len(doc['events'])} events, reason={doc['reason']})"
    )
    print("telemetry drill OK: mid-run scrape + fault kill + postmortem evidence")
    return 0


if __name__ == "__main__":
    sys.exit(main())
