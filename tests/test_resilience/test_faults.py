"""Unit tests for the fault-plan engine (sheeprl_tpu/resilience/faults.py)."""

import json

import pytest

from sheeprl_tpu.resilience import faults
from sheeprl_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    active_plan,
    clear_plan,
    fault_bytes,
    fault_point,
    install_from_config,
    install_from_env,
    install_plan,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    clear_plan()
    yield
    clear_plan()


class TestPlanBuild:
    def test_empty_plan_compiles_to_none(self):
        assert install_plan(FaultPlan.from_specs([])) is None
        assert active_plan() is None
        # the disabled hot path: must be callable with zero effect
        fault_point("env.step")
        assert fault_bytes("checkpoint.write_shard", b"abc") == b"abc"

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            FaultPlan.from_specs([{"site": "env.stpe", "kind": "raise", "at": 1}])

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan.from_specs([{"site": "env.step", "kind": "explode", "at": 1}])

    def test_missing_schedule_rejected(self):
        with pytest.raises(ValueError, match="no schedule"):
            FaultPlan.from_specs([{"site": "env.step", "kind": "raise"}])

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown fault spec fields"):
            FaultPlan.from_specs([{"site": "env.step", "kind": "raise", "att": 1}])

    def test_bad_exception_name_rejected(self):
        with pytest.raises(ValueError, match="not a builtin exception"):
            FaultPlan.from_specs(
                [{"site": "env.step", "kind": "raise", "at": 1, "exception": "Nope"}]
            )


class TestSchedules:
    def test_at_fires_exactly_once(self):
        install_plan(FaultPlan.from_specs([{"site": "env.step", "kind": "raise", "at": 3}]))
        fault_point("env.step")
        fault_point("env.step")
        with pytest.raises(InjectedFault):
            fault_point("env.step")
        for _ in range(10):
            fault_point("env.step")  # never again

    def test_every_fires_periodically_with_max_fires(self):
        install_plan(
            FaultPlan.from_specs(
                [{"site": "env.step", "kind": "raise", "every": 3, "max_fires": 2}]
            )
        )
        fired = 0
        for _ in range(12):
            try:
                fault_point("env.step")
            except InjectedFault:
                fired += 1
        assert fired == 2

    def test_p_schedule_is_seeded_deterministic(self):
        def run(seed):
            plan = FaultPlan.from_specs(
                [{"site": "env.step", "kind": "raise", "p": 0.3}], seed=seed
            )
            install_plan(plan)
            pattern = []
            for _ in range(50):
                try:
                    fault_point("env.step")
                    pattern.append(0)
                except InjectedFault:
                    pattern.append(1)
            return pattern

        a, b = run(7), run(7)
        assert a == b  # same seed, same storm
        assert run(8) != a  # different seed, different storm
        assert sum(a) > 0  # p=0.3 over 50 draws fires at least once

    def test_sites_are_independent(self):
        install_plan(
            FaultPlan.from_specs([{"site": "env.reset", "kind": "raise", "at": 1}])
        )
        fault_point("env.step")  # not targeted
        with pytest.raises(InjectedFault):
            fault_point("env.reset")

    def test_custom_exception_class(self):
        install_plan(
            FaultPlan.from_specs(
                [{"site": "checkpoint.write_shard", "kind": "raise", "at": 1,
                  "exception": "OSError", "message": "disk on fire"}]
            )
        )
        with pytest.raises(OSError, match="disk on fire"):
            fault_point("checkpoint.write_shard")


class TestByteFaults:
    def test_corrupt_changes_bytes_keeps_length(self):
        install_plan(
            FaultPlan.from_specs(
                [{"site": "checkpoint.write_shard", "kind": "corrupt", "at": 1}]
            )
        )
        payload = bytes(range(256)) * 4
        out = fault_bytes("checkpoint.write_shard", payload)
        assert len(out) == len(payload) and out != payload
        # next call: untouched
        assert fault_bytes("checkpoint.write_shard", payload) == payload

    def test_truncate_halves_payload(self):
        install_plan(
            FaultPlan.from_specs(
                [{"site": "checkpoint.write_shard", "kind": "truncate", "at": 1}]
            )
        )
        out = fault_bytes("checkpoint.write_shard", b"x" * 100)
        assert len(out) == 50

    def test_corrupt_at_value_site_rejected(self):
        # a byte fault at a value site would silently never act — reject at
        # plan build, like every other way to disarm a drill by typo
        with pytest.raises(ValueError, match="byte-payload sites"):
            FaultPlan.from_specs([{"site": "env.step", "kind": "corrupt", "every": 1}])


class TestInstallPaths:
    def test_env_var_roundtrip(self, monkeypatch):
        plan = FaultPlan.from_specs(
            [{"site": "serve.http", "kind": "latency", "every": 2, "seconds": 0.01}],
            seed=5,
        )
        monkeypatch.setenv(faults.ENV_VAR, plan.to_json())
        installed = install_from_env()
        assert installed is not None and installed.sites == ["serve.http"]
        assert installed.seed == 5

    def test_env_var_bare_list(self, monkeypatch):
        monkeypatch.setenv(
            faults.ENV_VAR,
            json.dumps([{"site": "env.step", "kind": "raise", "at": 1}]),
        )
        assert install_from_env().sites == ["env.step"]

    def test_install_from_config_disabled(self):
        assert install_from_config({"fault_injection": {"enabled": False, "plan": [
            {"site": "env.step", "kind": "raise", "at": 1}]}}) is None

    def test_install_from_config_enabled(self):
        plan = install_from_config(
            {
                "seed": 3,
                "fault_injection": {
                    "enabled": True,
                    "seed": None,
                    "plan": [{"site": "env.step", "kind": "raise", "at": 1}],
                },
            }
        )
        assert plan is not None and plan.sites == ["env.step"]
        assert plan.seed == 3  # falls back to the run seed

    def test_env_var_wins_over_config(self, monkeypatch):
        monkeypatch.setenv(
            faults.ENV_VAR,
            json.dumps([{"site": "serve.http", "kind": "raise", "at": 1}]),
        )
        plan = install_from_config(
            {"fault_injection": {"enabled": True,
                                 "plan": [{"site": "env.step", "kind": "raise", "at": 1}]}}
        )
        assert plan.sites == ["serve.http"]

    def test_targets_prefix(self):
        plan = FaultPlan.from_specs([{"site": "env.step", "kind": "raise", "at": 1}])
        assert plan.targets("env.")
        assert not plan.targets("serve.")
