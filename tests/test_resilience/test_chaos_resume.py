"""Chaos drill (b): SIGKILL a training run mid-checkpoint-write, corrupt the
newest COMMITTED snapshot on disk, then relaunch with
``checkpoint.resume_from=auto`` and assert:

* the torn snapshot (shards written, COMMIT missing — the injected hang
  parks the writer exactly in that window, so kill -9 lands mid-protocol)
  is never eligible for resume;
* the CRC-corrupted COMMITTED snapshot is quarantined
  (``step_* → step_*.corrupt``), NOT loaded;
* the run resumes from the last INTACT committed step and completes.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.checkpoint import list_checkpoints
from sheeprl_tpu.checkpoint.protocol import checkpoint_step, shard_name

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_COMMON = [
    "exp=sac",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "env.max_episode_steps=8",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "algo.per_rank_batch_size=4",
    "algo.learning_starts=4",
    "algo.mlp_keys.encoder=[state]",
    "algo.run_test=False",
    "checkpoint.every=20",
    "buffer.size=512",
    "buffer.memmap=False",
    "buffer.checkpoint=True",
    "metric.log_level=0",
    "root_dir=chaos_resume",
    "print_config=False",
]

# the 3rd commit hangs BETWEEN the shard writes and the COMMIT marker: the
# parent's kill -9 then lands deterministically mid-protocol, leaving the
# canonical torn snapshot
HANG_COMMIT_PLAN = json.dumps(
    {"plan": [{"site": "checkpoint.commit", "kind": "hang", "at": 3, "seconds": 300.0}]}
)


def _launch(tmp_path, run_name, total_steps, fault_plan=None, extra=()):
    code = "import sys; from sheeprl_tpu.cli import run; run(sys.argv[1:])"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    env.pop("SHEEPRL_FAULT_PLAN", None)
    if fault_plan is not None:
        env["SHEEPRL_FAULT_PLAN"] = fault_plan
    args = [
        *_COMMON,
        f"algo.total_steps={total_steps}",
        f"log_dir={tmp_path}/logs",
        f"run_name={run_name}",
        *extra,
    ]
    return subprocess.Popen(
        [sys.executable, "-c", code, *args],
        env=env,
        cwd=_REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _committed(tmp_path):
    out = []
    for root in glob.glob(f"{tmp_path}/logs/**/checkpoint", recursive=True):
        out.extend(list_checkpoints(root))
    return sorted(out, key=checkpoint_step)


def _torn(tmp_path):
    """Snapshot dirs whose shards landed but whose COMMIT never did — the
    injected commit hang parks the writer exactly in that window."""
    out = []
    for root in glob.glob(f"{tmp_path}/logs/**/checkpoint", recursive=True):
        out.extend(
            d
            for d in list_checkpoints(root, committed_only=False)
            if (d / shard_name(0)).exists() and not (d / "COMMIT").exists()
        )
    return out


def test_sigkill_mid_write_quarantine_and_auto_resume(tmp_path):
    # ---- phase 1: train, hang the 3rd commit, kill -9 mid-protocol --------
    proc = _launch(tmp_path, "run_a", total_steps=100000, fault_plan=HANG_COMMIT_PLAN)
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if len(_committed(tmp_path)) >= 2 and _torn(tmp_path):
                break  # 2 durable commits + the hung 3rd (shards, no COMMIT)
            if proc.poll() is not None:
                raise AssertionError(
                    f"training died early (rc={proc.returncode}):\n{proc.stdout.read()}"
                )
            time.sleep(0.25)
        else:
            raise AssertionError(
                f"never reached 2 commits + a parked 3rd; have "
                f"{len(_committed(tmp_path))} commits, torn={_torn(tmp_path)}"
            )
        os.kill(proc.pid, signal.SIGKILL)  # no grace, no final save
        proc.wait(30)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()

    committed = _committed(tmp_path)
    assert len(committed) >= 2
    torn = _torn(tmp_path)
    assert torn, "the injected commit hang must leave a torn snapshot"
    survivor_step = checkpoint_step(committed[-2])
    newest_step = checkpoint_step(committed[-1])

    # ---- phase 2: bit-rot the newest COMMITTED snapshot -------------------
    shard = committed[-1] / shard_name(0)
    blob = bytearray(shard.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    shard.write_bytes(bytes(blob))

    # ---- phase 3: auto-resume must quarantine the rot and take the last
    # intact commit, then run to completion ---------------------------------
    resume_steps = newest_step + 40  # a bit more work, then a clean finish
    proc = _launch(
        tmp_path, "run_b", total_steps=resume_steps,
        extra=("checkpoint.resume_from=auto",),
    )
    out = ""
    try:
        out = proc.communicate(timeout=300)[0]
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, f"resumed run failed:\n{out}"

    # the damaged snapshot was quarantined, not loaded
    quarantined = glob.glob(f"{tmp_path}/logs/**/step_*.corrupt", recursive=True)
    assert quarantined, f"no quarantined snapshot; output:\n{out}"
    assert f"step_{newest_step:012d}.corrupt" in quarantined[0]
    # resume landed on the last INTACT committed step
    assert f"resume_from=auto -> " in out
    assert f"step_{survivor_step:012d}" in out.split("resume_from=auto -> ", 1)[1].splitlines()[0]
    # the torn snapshot stayed uncommitted and was never resumed from
    assert f"step_{checkpoint_step(torn[0]):012d}" not in out.split("resume_from=auto -> ", 1)[1].splitlines()[0]
    # and the resumed run itself committed new progress past the survivor
    final = _committed(tmp_path)
    assert checkpoint_step(final[-1]) > survivor_step
