"""Chaos drill (a): an env crash + hang storm mid-training must not kill the
run — crashes restart through ``RestartOnException`` (inside the async
workers), the injected hang trips the vector-level step-deadline watchdog
(teardown + recreate), and the train loop patches its sequence replay via
the ``restart_on_exception`` flag (``repair_tail``).  The run completes.
"""

import json

import pytest

from sheeprl_tpu.resilience import faults

# worker-side storm: every worker crashes on its 10th/20th/30th step (caught
# by RestartOnException inside the worker) and wedges for 30s on its 25th
# (caught by the parent-side step-deadline watchdog).  The plan rides the
# SHEEPRL_FAULT_PLAN env var across the fork into the vector workers.
STORM_PLAN = json.dumps(
    {
        "seed": 11,
        "plan": [
            {"site": "env.step", "kind": "raise", "every": 10, "max_fires": 3},
            {"site": "env.step", "kind": "hang", "at": 25, "seconds": 30.0, "max_fires": 1},
        ],
    }
)


@pytest.mark.filterwarnings("ignore::RuntimeWarning")
def test_env_crash_and_hang_storm_completes_training(tmp_path, monkeypatch):
    from sheeprl_tpu.cli import run
    from sheeprl_tpu.data import buffers
    from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

    monkeypatch.setenv(faults.ENV_VAR, STORM_PLAN)

    repairs = []
    orig_repair = buffers.EnvIndependentReplayBuffer.repair_tail

    def counting_repair(self, env):
        repairs.append(env)
        return orig_repair(self, env)

    monkeypatch.setattr(buffers.EnvIndependentReplayBuffer, "repair_tail", counting_repair)

    stalls_before = RESILIENCE_MONITOR.totals()["stalls"]
    try:
        run(
            [
                "exp=dreamer_v3",
                "algo=dreamer_v3_XS",
                "env=dummy",
                "env.id=discrete_dummy",
                "env.num_envs=2",
                # the storm needs the REAL async path: restart wrapper inside
                # the workers, hang watchdog at the vector level
                "env.sync_env=False",
                "env.restart_on_exception=True",
                "env.step_deadline_s=2.0",
                "env.max_vecenv_restarts=2",
                "env.capture_video=False",
                "algo.per_rank_batch_size=2",
                "algo.per_rank_sequence_length=8",
                "algo.horizon=4",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[state]",
                "algo.world_model.encoder.cnn_channels_multiplier=4",
                "algo.dense_units=16",
                "algo.world_model.recurrent_model.recurrent_state_size=16",
                "algo.world_model.transition_model.hidden_size=16",
                "algo.world_model.representation_model.hidden_size=16",
                "algo.learning_starts=8",
                "algo.total_steps=64",
                "algo.replay_ratio=0.1",
                "algo.run_test=False",
                "fabric.devices=1",
                "fabric.accelerator=cpu",
                "checkpoint.every=0",
                "checkpoint.save_last=False",
                "buffer.memmap=False",
                "metric.log_level=0",
                f"log_dir={tmp_path}/logs",
                "print_config=False",
            ]
        )
    finally:
        faults.clear_plan()

    # the hang tripped the parent-side watchdog (teardown + recreate)...
    assert RESILIENCE_MONITOR.totals()["stalls"] > stalls_before
    assert RESILIENCE_MONITOR.totals()["env_restarts"] > 0
    # ...and broken streams (worker crashes and/or the teardown) were
    # patched in the replay buffer instead of bootstrapping across the break
    assert len(repairs) > 0
