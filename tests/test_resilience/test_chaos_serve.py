"""Chaos drill (c): a serve load test under an active fault plan.

* injected HTTP faults (500s + latency) on ``serve.http`` must drop ZERO
  in-flight requests — the client's retry/backoff absorbs them;
* a poisoned (corrupt) newer commit must open the reload circuit breaker,
  be quarantined, and leave the OLD params serving — ``/healthz`` reports
  ``degraded: true`` while ``/v1/act`` keeps answering.
"""

import json
import shutil
import threading

import numpy as np
import pytest

from sheeprl_tpu.resilience import faults

pytestmark = pytest.mark.filterwarnings("ignore::RuntimeWarning")


@pytest.fixture(scope="module")
def ppo_ckpt(tmp_path_factory):
    from sheeprl_tpu.cli import run
    from tests.ckpt_utils import find_checkpoints

    log_dir = tmp_path_factory.mktemp("chaos_serve")
    run(
        [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "fabric.accelerator=cpu",
            "metric.log_level=0",
            "checkpoint.every=1",
            "buffer.memmap=False",
            "algo.learning_starts=0",
            f"log_dir={log_dir}",
            "print_config=False",
            "algo.run_test=False",
        ]
    )
    ckpts = find_checkpoints(str(log_dir))
    assert ckpts
    return ckpts[-1]


def _service(ppo_ckpt, overrides=()):
    from sheeprl_tpu.serve.service import PolicyService

    return PolicyService.from_checkpoint(
        ppo_ckpt,
        [
            "serve.watch_commits=false",  # polls driven explicitly by the test
            "serve.max_wait_ms=2.0",
            "serve.reload_failure_threshold=2",
            "serve.reload_breaker_reset_s=30.0",
            *overrides,
        ],
    )


def test_load_test_under_fault_plan_drops_nothing(ppo_ckpt):
    """16 client threads × 8 requests against a server whose HTTP layer is
    actively failing (every 7th request 500s, ~10% get +50 ms latency):
    every single request must still produce an action."""
    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.server import PolicyServer

    service = _service(ppo_ckpt)
    obs = {
        k: np.zeros(shape, dtype=dt)
        for k, (shape, dt) in service.player.obs_spec.items()
    }
    faults.install_plan(
        faults.FaultPlan.from_specs(
            [
                {"site": "serve.http", "kind": "raise", "every": 7},
                {"site": "serve.http", "kind": "latency", "p": 0.1, "seconds": 0.05},
            ],
            seed=13,
        )
    )
    try:
        with PolicyServer(service) as server:
            client_errors = []
            actions = []
            lock = threading.Lock()

            def worker(wid):
                client = PolicyClient(server.url, timeout=30.0, retries=5, retry_base_s=0.05)
                for _ in range(8):
                    try:
                        a = client.act(obs)
                        with lock:
                            actions.append(a)
                    except Exception as e:  # a dropped request
                        with lock:
                            client_errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            stats = service.stats()
    finally:
        faults.clear_plan()

    assert client_errors == [], f"dropped {len(client_errors)}: {client_errors[:3]}"
    assert len(actions) == 16 * 8  # zero in-flight requests lost
    assert stats["served"] >= 16 * 8
    # the storm really happened: injected 500s were retried, not absorbed
    from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

    totals = RESILIENCE_MONITOR.totals()
    assert totals["injected_by_site"].get("serve.http", 0) > 0
    assert totals["retries"] > 0


def test_poisoned_commit_opens_breaker_quarantines_and_keeps_serving(ppo_ckpt):
    import pathlib

    from sheeprl_tpu.checkpoint.protocol import checkpoint_step, shard_name
    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.server import PolicyServer

    service = _service(ppo_ckpt)
    served_step = service.store.step
    ckpt_dir = pathlib.Path(ppo_ckpt)
    root = ckpt_dir.parent

    # forge a NEWER commit whose shard bytes are garbage (bit rot / torn
    # write that still carries a COMMIT marker)
    poison = root / f"step_{served_step + 1000:012d}"
    shutil.copytree(ckpt_dir, poison)
    shard = poison / shard_name(0)
    blob = bytearray(shard.read_bytes())
    blob[len(blob) // 3] ^= 0xFF
    blob[len(blob) // 2] ^= 0xFF
    shard.write_bytes(bytes(blob))

    obs = {
        k: np.zeros(shape, dtype=dt)
        for k, (shape, dt) in service.player.obs_spec.items()
    }
    with PolicyServer(service) as server:
        client = PolicyClient(server.url)
        assert client.health()["degraded"] is False

        # failure_threshold=2: two failed loads of the same poisoned step →
        # breaker opens + snapshot quarantined; old params keep serving
        assert service.watcher.poll_once() is None
        assert service.watcher.poll_once() is None

        health = client.health()
        assert health["degraded"] is True
        assert health["reload_breaker"]["state"] == "open"
        stats = client.stats()
        assert stats["degraded"] is True
        assert stats["quarantined"] == 1
        assert stats["checkpoint_step"] == served_step  # old params still in

        # the poison is out of the discovery namespace, kept for forensics
        assert not poison.exists()
        corrupt = list(root.glob("step_*.corrupt*"))
        assert len(corrupt) == 1 and checkpoint_step(corrupt[0]) == -1

        # and the server still answers with the old params
        a = client.act(obs)
        assert np.asarray(a).size >= 1
        assert client.last_checkpoint_step == served_step


def test_hot_reload_still_works_after_quarantine(ppo_ckpt):
    """After the poison is quarantined, a GOOD newer commit must hot-swap
    once the breaker's cool-down lets the half-open probe through."""
    import pathlib

    from sheeprl_tpu.checkpoint.protocol import checkpoint_step

    service = _service(ppo_ckpt, overrides=("serve.reload_breaker_reset_s=0.05",))
    served_step = service.store.step
    ckpt_dir = pathlib.Path(ppo_ckpt)
    root = ckpt_dir.parent

    poison = root / f"step_{served_step + 500:012d}"
    shutil.copytree(ckpt_dir, poison)
    (poison / "shard_r00000.pkl").write_bytes(b"not a pickle")

    service.start(warm=False)
    try:
        assert service.watcher.poll_once() is None
        assert service.watcher.poll_once() is None  # threshold=2 → quarantined
        assert not poison.exists()

        good = root / f"step_{served_step + 600:012d}"
        shutil.copytree(ckpt_dir, good)
        import time

        time.sleep(0.06)  # breaker cool-down → half-open probe allowed
        gen = service.watcher.poll_once()
        assert gen is not None
        assert service.store.step == served_step + 600
        assert service.watcher.degraded is False  # probe success closed it
    finally:
        service.stop()
