"""Unit tests for the recovery primitives (sheeprl_tpu/resilience/retry.py)
and the hardened checkpoint writer paths that use them."""

import threading
import time

import pytest

from sheeprl_tpu.checkpoint.writer import AsyncCheckpointWriter
from sheeprl_tpu.resilience.retry import CircuitBreaker, Watchdog, retry


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("blip")
            return "ok"

        assert retry(flaky, attempts=5, base_s=0.001) == "ok"
        assert len(calls) == 3

    def test_gives_up_after_attempts(self):
        calls = []

        def dead():
            calls.append(1)
            raise OSError("gone")

        with pytest.raises(OSError, match="gone"):
            retry(dead, attempts=3, base_s=0.001)
        assert len(calls) == 3

    def test_non_transient_propagates_immediately(self):
        calls = []

        def wrong():
            calls.append(1)
            raise ValueError("bug, not blip")

        with pytest.raises(ValueError):
            retry(wrong, attempts=5, base_s=0.001, retry_on=(OSError,))
        assert len(calls) == 1

    def test_should_retry_filter(self):
        calls = []

        def teapot():
            calls.append(1)
            raise OSError(418, "teapot")

        with pytest.raises(OSError):
            retry(
                teapot,
                attempts=5,
                base_s=0.001,
                should_retry=lambda e: e.args[0] != 418,
            )
        assert len(calls) == 1

    def test_deadline_bounds_total_time(self):
        t0 = time.monotonic()
        with pytest.raises(OSError):
            retry(
                lambda: (_ for _ in ()).throw(OSError("x")),
                attempts=100,
                base_s=0.5,
                multiplier=1.0,
                jitter=0.0,
                deadline_s=0.3,
            )
        assert time.monotonic() - t0 < 1.0

    def test_backoff_grows(self):
        sleeps = []
        calls = []

        def dead():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(OSError):
            retry(
                dead,
                attempts=4,
                base_s=0.01,
                multiplier=2.0,
                jitter=0.0,
                on_retry=lambda n, e, s: sleeps.append(s),
            )
        assert sleeps == [0.01, 0.02, 0.04]


class TestWatchdog:
    def test_detects_stall_once_and_rearms_on_beat(self):
        stalls = []
        wd = Watchdog(0.08, on_stall=stalls.append, interval_s=0.02)
        try:
            wd.arm()
            time.sleep(0.3)
            assert len(stalls) == 1  # fires once per stall, not per check
            wd.beat()  # progress: re-arms
            time.sleep(0.3)
            assert len(stalls) == 2
        finally:
            wd.close()

    def test_no_stall_while_beating_or_disarmed(self):
        stalls = []
        wd = Watchdog(0.1, on_stall=stalls.append, interval_s=0.02)
        try:
            wd.arm()
            for _ in range(10):
                wd.beat()
                time.sleep(0.02)
            wd.disarm()
            time.sleep(0.25)
            assert stalls == []
        finally:
            wd.close()

    def test_context_manager(self):
        stalls = []
        wd = Watchdog(10.0, on_stall=stalls.append, interval_s=0.02)
        try:
            with wd.watching() as w:
                assert w is wd
            time.sleep(0.1)
            assert stalls == []
        finally:
            wd.close()


class TestCircuitBreaker:
    def test_open_half_open_close_cycle(self):
        b = CircuitBreaker(failure_threshold=2, reset_timeout_s=0.1)
        assert b.state == CircuitBreaker.CLOSED and b.allow()
        b.record_failure()
        assert b.state == CircuitBreaker.CLOSED
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN and not b.allow()
        time.sleep(0.12)
        assert b.state == CircuitBreaker.HALF_OPEN and b.allow()
        b.record_success()
        assert b.state == CircuitBreaker.CLOSED
        assert b.failures == 0

    def test_half_open_failure_reopens(self):
        b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        b.record_failure()
        assert not b.allow()
        time.sleep(0.06)
        assert b.allow()  # half-open probe
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN  # immediately, one strike
        assert b.opens == 2

    def test_snapshot_shape(self):
        b = CircuitBreaker(failure_threshold=3, name="t")
        b.record_failure()
        snap = b.snapshot()
        assert snap == {"state": "closed", "failures": 1, "threshold": 3, "opens": 0}


class TestHardenedWriter:
    def test_transient_io_error_retried_not_parked(self):
        w = AsyncCheckpointWriter(queue_size=2, io_retries=3, io_retry_base_s=0.001)
        calls = []

        def job():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("blip")
            return 7

        w.submit(job)
        assert w.flush(10.0)
        assert len(calls) == 3
        w.close(5.0)  # no parked error to re-raise

    def test_exhausted_retries_park_and_reraise(self):
        w = AsyncCheckpointWriter(queue_size=2, io_retries=2, io_retry_base_s=0.001)
        w.submit(lambda: (_ for _ in ()).throw(OSError("dead disk")))
        with pytest.raises(RuntimeError, match="async checkpoint save failed"):
            w.flush(10.0)
        w.close(5.0)

    def test_non_io_error_not_retried(self):
        w = AsyncCheckpointWriter(queue_size=2, io_retries=5, io_retry_base_s=0.001)
        calls = []

        def job():
            calls.append(1)
            raise ValueError("bug")

        w.submit(job)
        with pytest.raises(RuntimeError):
            w.flush(10.0)
        assert len(calls) == 1
        w.close(5.0)

    def test_close_returns_with_wedged_worker(self):
        """The close-on-wedged-worker satellite: a worker stuck in a job
        (dead disk) must not hang interpreter shutdown — close() drains via
        the bounded waits, warns about the abandoned job, and returns."""
        release = threading.Event()
        w = AsyncCheckpointWriter(queue_size=1, io_retries=1, hang_warn_s=0)

        def wedged():
            release.wait(30.0)  # simulates a write stuck on dead storage
            return 0

        w.submit(wedged)
        w.submit(lambda: 0)  # fills the bounded queue behind the stuck job
        t0 = time.monotonic()
        with pytest.warns(RuntimeWarning, match="abandoning the daemon thread"):
            w.close(timeout_s=0.3)
        assert time.monotonic() - t0 < 10.0  # returned, did not hang
        release.set()  # let the daemon thread finish so the test exits clean

    def test_writer_watchdog_flags_wedged_job(self):
        from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

        before = RESILIENCE_MONITOR.totals()["stalls"]
        release = threading.Event()
        w = AsyncCheckpointWriter(queue_size=1, io_retries=1, hang_warn_s=0.05)
        with pytest.warns(RuntimeWarning, match="no progress"):
            w.submit(lambda: release.wait(1.0))
            time.sleep(0.4)
        release.set()
        w.flush(5.0)
        w.close(5.0)
        assert RESILIENCE_MONITOR.totals()["stalls"] > before
