"""Training-health sentinel tests (sheeprl_tpu/resilience/health.py):
the in-trace non-finite guard, the divergence detector, the planted
``update.grads`` fault surface, and the SAC end-to-end drills."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.compile import compile_once
from sheeprl_tpu.resilience.faults import FaultPlan, clear_plan, install_plan
from sheeprl_tpu.resilience.health import HealthSentinel, HealthState
from sheeprl_tpu.telemetry import HUB, RECORDER


@pytest.fixture(autouse=True)
def _clean():
    clear_plan()
    HUB.unregister("health")
    yield
    clear_plan()
    HUB.unregister("health")


def toy_phase(p, o, batch, k, c):
    """Canonical train-phase convention: (p, o, *data) -> (p, o, metrics)."""
    g = jnp.mean(batch) * jnp.ones_like(p["w"])
    return {"w": p["w"] - 0.1 * g}, o + 1, (jnp.mean(batch),)


def run_windows(sentinel, n, batches=None, phase=toy_phase):
    guarded = compile_once(sentinel.wrap(phase), name="toy_guarded")
    h = sentinel.init_state()
    p = {"w": jnp.ones((4,))}
    o = jnp.int32(0)
    k = jax.random.PRNGKey(0)
    history = [np.asarray(p["w"]).copy()]
    for i in range(n):
        batch = jnp.full((8,), 1.0 if batches is None else float(batches[i]))
        h, p, o, m = guarded(h, p, o, batch, k, jnp.int32(i))
        history.append(np.asarray(p["w"]).copy())
    return guarded, h, history


class TestNonFiniteGuard:
    def test_clean_updates_apply_exactly(self):
        s = HealthSentinel({})
        guarded, h, hist = run_windows(s, 3)
        # every window applied: params move every step, counters agree
        assert all(not np.array_equal(a, b) for a, b in zip(hist, hist[1:]))
        vals = jax.device_get(h)
        assert int(vals.applied) == 3 and int(vals.skipped) == 0

    def test_planted_nonfinite_window_skipped_bit_identically(self):
        install_plan(
            FaultPlan.from_specs([{"site": "update.grads", "kind": "nonfinite", "at": 2}])
        )
        s = HealthSentinel({})
        guarded, h, hist = run_windows(s, 3)
        # window 2 poisoned -> params bit-identical across it...
        assert np.array_equal(hist[1], hist[2])
        # ...and the run continues applying afterwards
        assert not np.array_equal(hist[2], hist[3])
        vals = jax.device_get(h)
        assert int(vals.skipped) == 1 and int(vals.applied) == 2
        assert int(vals.nonfinite_loss) == 1
        # ONE executable across clean and poisoned windows
        assert guarded.cache_size() == 1

    def test_naturally_nonfinite_loss_skipped_without_any_plan(self):
        s = HealthSentinel({})

        def nan_on_neg(p, o, batch, k, c):
            g = jnp.mean(batch)
            g = jnp.where(g < 0, jnp.float32(jnp.nan), g)
            return {"w": p["w"] - 0.1 * g * jnp.ones_like(p["w"])}, o, (g,)

        _, h, hist = run_windows(s, 3, batches=[1.0, -1.0, 1.0], phase=nan_on_neg)
        assert np.array_equal(hist[1], hist[2])  # NaN window skipped
        assert int(jax.device_get(h).skipped) == 1

    def test_loss_only_check_misses_finite_loss_nan_params_when_disabled(self):
        # check_params=True (default) catches NaN params under a finite
        # loss; with it off the wrapper trusts the loss alone
        def nan_params(p, o, batch, k, c):
            return {"w": p["w"] + jnp.float32(jnp.nan)}, o, (jnp.float32(1.0),)

        _, h_on, hist_on = run_windows(HealthSentinel({}), 1, phase=nan_params)
        assert int(jax.device_get(h_on).skipped) == 1
        assert np.isfinite(hist_on[1]).all()
        _, h_off, hist_off = run_windows(
            HealthSentinel({"check_params": False}), 1, phase=nan_params
        )
        assert int(jax.device_get(h_off).skipped) == 0
        assert not np.isfinite(hist_off[1]).any()


class TestDivergenceDetector:
    def _sentinel(self, action="rollback"):
        return HealthSentinel(
            {
                "min_windows": 2,
                "patience": 2,
                "spike_factor": 2.0,
                "spike_min": 0.1,
                "ema_decay": 0.5,
                "poll_every_updates": 1,
                "divergence": {"action": action},
            }
        )

    def test_consecutive_spikes_latch_diverged(self):
        s = self._sentinel()
        # warmup at loss~1, then 3 consecutive 100x windows
        _, h, _ = run_windows(s, 6, batches=[1, 1, 1, 100, 100, 100])
        vals = jax.device_get(h)
        assert int(vals.diverged) == 1
        assert int(vals.spike_total) >= 2
        assert s.poll(h, policy_step=123) == "rollback"

    def test_single_spike_does_not_latch(self):
        s = self._sentinel()
        _, h, _ = run_windows(s, 6, batches=[1, 1, 1, 100, 1, 1])
        assert int(jax.device_get(h).diverged) == 0
        assert s.poll(h, 123) == "none"

    def test_action_none_reports_but_never_rolls_back(self):
        s = self._sentinel(action="none")
        _, h, _ = run_windows(s, 6, batches=[1, 1, 1, 100, 100, 100])
        with pytest.warns(RuntimeWarning, match="diverged"):
            assert s.poll(h, 123) == "none"
        assert s.metrics()["Health/diverged"] == 1.0

    def test_planted_divergence_fault_trips_detector(self):
        # the fault must land AFTER the min_windows warmup: the EMA has a
        # clean baseline by window 5, so the planted 1e6x loss is a spike.
        # The plan must be installed before the sentinel is built — specs
        # are resolved into the trace at wrap time.
        install_plan(
            FaultPlan.from_specs([{"site": "update.grads", "kind": "divergence", "at": 5}])
        )
        s = HealthSentinel(
            {
                "min_windows": 4,
                "patience": 1,
                "spike_factor": 2.0,
                "spike_min": 0.1,
                "divergence": {"action": "rollback", "fault_scale": 1e6},
            }
        )
        _, h, _ = run_windows(s, 6)
        assert int(jax.device_get(h).diverged) == 1

    def test_reseed_preserves_dispatch_counter(self):
        s = self._sentinel()
        _, h, _ = run_windows(s, 6, batches=[1, 1, 1, 100, 100, 100])
        assert s.poll(h, 1) == "rollback"
        h2 = s.reseed_state()
        vals = jax.device_get(h2)
        assert int(vals.dispatches) == 6  # schedules/warmup do not replay
        assert int(vals.diverged) == 0  # the sticky flag cleared
        assert s.begin_rollback(1) is None  # within budget

    def test_rollback_budget_raises(self):
        from sheeprl_tpu.resilience.health import DivergenceError

        s = HealthSentinel({"divergence": {"action": "rollback", "max_rollbacks": 1}})
        s.begin_rollback(1)
        with pytest.raises(DivergenceError, match="exhausted"):
            s.begin_rollback(2)


class TestTelemetryPlumbing:
    def test_health_metrics_flow_through_hub(self):
        install_plan(
            FaultPlan.from_specs([{"site": "update.grads", "kind": "nonfinite", "at": 1}])
        )
        s = HealthSentinel({}).register()
        _, h, _ = run_windows(s, 2)
        s.poll(h, policy_step=10)
        merged = HUB.flush()
        assert merged["Health/skipped"] == 1.0
        assert merged["Health/windows"] == 2.0
        s.close()
        assert "Health/skipped" not in HUB.flush()

    def test_poll_records_recorder_events_and_injections(self):
        from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

        install_plan(
            FaultPlan.from_specs([{"site": "update.grads", "kind": "nonfinite", "at": 2}])
        )
        RECORDER.clear()
        before = RESILIENCE_MONITOR.totals()["injected"]
        s = HealthSentinel({})
        _, h, _ = run_windows(s, 3)
        s.poll(h, policy_step=42)
        kinds = [e["kind"] for e in RECORDER.snapshot()]
        assert "health.skip" in kinds
        injected = [e for e in RECORDER.snapshot() if e["kind"] == "fault.injected"]
        assert any(e.get("site") == "update.grads" for e in injected)
        assert RESILIENCE_MONITOR.totals()["injected"] == before + 1
        # polling again without new dispatches records nothing new
        n = len(RECORDER.snapshot())
        s.poll(h, policy_step=43)
        assert len(RECORDER.snapshot()) == n


class TestFaultSpecValidation:
    def test_trace_kind_at_host_site_rejected(self):
        with pytest.raises(ValueError, match="do not match"):
            FaultPlan.from_specs([{"site": "env.step", "kind": "nonfinite", "at": 1}])

    def test_host_kind_at_trace_site_rejected(self):
        with pytest.raises(ValueError, match="do not match"):
            FaultPlan.from_specs([{"site": "update.grads", "kind": "raise", "at": 1}])

    def test_probability_schedule_rejected_at_trace_site(self):
        with pytest.raises(ValueError, match="deterministic"):
            FaultPlan.from_specs([{"site": "update.grads", "kind": "nonfinite", "p": 0.5}])

    def test_specs_for_does_not_advance_counters(self):
        plan = FaultPlan.from_specs(
            [{"site": "update.grads", "kind": "nonfinite", "at": 1}]
        )
        assert len(plan.specs_for("update.grads")) == 1
        assert plan.specs_for("update.grads")[0]._calls == 0
        assert plan.specs_for("env.step") == []


class TestDisabled:
    def test_from_config_disabled_returns_none(self):
        from sheeprl_tpu.utils.structured import dotdict

        assert HealthSentinel.from_config(dotdict({"health": {"enabled": False}})) is None
        assert HealthSentinel.from_config(dotdict({"health": {"enabled": True}})) is not None
        assert HealthSentinel.from_config(dotdict({})) is not None  # default ON


@pytest.mark.slow
class TestSacEndToEnd:
    COMMON = [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.learning_starts=8",
        "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=8",
        "algo.run_test=False",
        "fabric.devices=1",
        "fabric.accelerator=cpu",
        "buffer.memmap=False",
        "buffer.size=512",
        "metric.log_level=1",
        "metric.log_every=1",
        "print_config=False",
    ]

    def test_injected_nonfinite_skips_update_mid_training(self, tmp_path, monkeypatch):
        """Acceptance drill: a planted update.grads nonfinite fault mid-run
        is skipped, reported through the hub, and leaves recorder
        evidence — and the run completes."""
        import json as _json

        from sheeprl_tpu.cli import run

        monkeypatch.setenv(
            "SHEEPRL_FAULT_PLAN",
            _json.dumps({"plan": [{"site": "update.grads", "kind": "nonfinite", "at": 3}]}),
        )
        run(
            self.COMMON
            + [
                "algo.total_steps=48",
                "checkpoint.every=0",
                "checkpoint.save_last=False",
                "health.poll_every_updates=2",
                f"log_dir={tmp_path}",
            ]
        )
        kinds = [e["kind"] for e in RECORDER.snapshot()]
        assert "health.skip" in kinds, kinds
        injected = [e for e in RECORDER.snapshot() if e["kind"] == "fault.injected"]
        assert any(e.get("site") == "update.grads" for e in injected)

    def test_divergence_rolls_back_to_committed_snapshot(self, tmp_path, monkeypatch):
        """Acceptance drill: a planted loss spike trips the detector and
        the loop restores the last committed checkpoint instead of
        continuing on garbage params."""
        import json as _json

        from sheeprl_tpu.cli import run

        monkeypatch.setenv(
            "SHEEPRL_FAULT_PLAN",
            _json.dumps({"plan": [{"site": "update.grads", "kind": "divergence", "at": 6}]}),
        )
        run(
            self.COMMON
            + [
                "algo.total_steps=64",
                "checkpoint.every=4",
                "checkpoint.async_save=False",
                "health.poll_every_updates=1",
                "health.min_windows=2",
                "health.patience=1",
                "health.spike_factor=2.0",
                "health.spike_min=0.1",
                "health.divergence.action=rollback",
                f"log_dir={tmp_path}",
            ]
        )
        events = RECORDER.snapshot()
        kinds = [e["kind"] for e in events]
        assert "health.diverged" in kinds, kinds
        rollbacks = [e for e in events if e["kind"] == "health.rollback"]
        assert rollbacks, kinds
        # rolled back onto a real committed snapshot of THIS run
        assert "step_" in rollbacks[0]["resume_step"]
