"""StepDeadlineVectorEnv: hang detection, teardown/recreate, restart budget."""

import os

import gymnasium as gym
import numpy as np
import pytest
from gymnasium.vector import AutoresetMode

from sheeprl_tpu.utils.env import StepDeadlineVectorEnv


class SometimesHangs(gym.Env):
    """Hangs on the step number given by the HANG_AT_STEP env var (the env
    var crosses the fork into AsyncVectorEnv workers)."""

    observation_space = gym.spaces.Box(-1, 1, (3,), np.float32)
    action_space = gym.spaces.Discrete(2)

    def __init__(self):
        self.t = 0

    def reset(self, seed=None, options=None):
        return np.zeros(3, np.float32), {}

    def step(self, action):
        self.t += 1
        if self.t == int(os.environ.get("HANG_AT_STEP", -1)):
            import time

            time.sleep(60.0)
        return np.full(3, self.t, np.float32), 1.0, False, False, {}


def _make(n=2):
    return gym.vector.AsyncVectorEnv(
        [SometimesHangs for _ in range(n)], autoreset_mode=AutoresetMode.SAME_STEP
    )


def test_normal_stepping_passes_through(monkeypatch):
    monkeypatch.setenv("HANG_AT_STEP", "-1")
    env = StepDeadlineVectorEnv(_make, deadline_s=5.0)
    try:
        obs, info = env.reset()
        for i in range(3):
            obs, r, term, trunc, info = env.step(np.zeros(2, np.int64))
            assert "restart_on_exception" not in info
            assert np.allclose(obs[:, 0], i + 1)
        assert env.num_envs == 2
        assert env.single_observation_space.shape == (3,)
    finally:
        env.close()


def test_hang_detected_torn_down_and_flagged(monkeypatch):
    monkeypatch.setenv("HANG_AT_STEP", "2")
    env = StepDeadlineVectorEnv(_make, deadline_s=1.0, max_restarts=1, window_s=60.0)
    try:
        env.reset()
        env.step(np.zeros(2, np.int64))  # t=1: fine
        monkeypatch.setenv("HANG_AT_STEP", "-1")  # recreated workers behave
        with pytest.warns(RuntimeWarning, match="vector env watchdog"):
            obs, r, term, trunc, info = env.step(np.zeros(2, np.int64))  # t=2 hangs
        # the break is reported on the RestartOnException contract so train
        # loops patch their replay tails
        assert np.all(np.asarray(info["restart_on_exception"]))
        assert not term.any() and not trunc.any()
        assert obs.shape == (2, 3)
        # the recreated vector env serves steps again
        obs, *_ = env.step(np.zeros(2, np.int64))
        assert np.allclose(obs[:, 0], 1.0)  # fresh envs, t restarted
    finally:
        env.close()


def test_restart_budget_exhaustion_raises(monkeypatch):
    monkeypatch.setenv("HANG_AT_STEP", "1")  # every worker generation hangs
    env = StepDeadlineVectorEnv(_make, deadline_s=0.5, max_restarts=1, window_s=600.0)
    try:
        env.reset()
        with pytest.warns(RuntimeWarning, match="vector env watchdog"):
            env.step(np.zeros(2, np.int64))  # restart 1: allowed
        with pytest.raises(RuntimeError, match="giving up"):
            env.step(np.zeros(2, np.int64))  # restart 2: budget spent
    finally:
        try:
            env.close(terminate=True)
        except Exception:
            pass


def test_reset_deadline_also_guarded(monkeypatch):
    monkeypatch.setenv("HANG_AT_STEP", "-1")
    env = StepDeadlineVectorEnv(_make, deadline_s=5.0)
    try:
        obs, info = env.reset()
        assert obs.shape == (2, 3)
    finally:
        env.close()


def test_vectorize_wires_watchdog_from_config():
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.utils.env import make_env, vectorize

    cfg = compose(
        [
            "exp=ppo", "env=dummy", "env.id=discrete_dummy", "env.num_envs=2",
            "env.sync_env=False", "env.capture_video=False",
            "env.step_deadline_s=7.5", "metric.log_level=0",
        ]
    )
    envs = vectorize(cfg, [make_env(cfg, 0, 0) for _ in range(2)])
    try:
        assert isinstance(envs, StepDeadlineVectorEnv)
        assert envs._deadline == 7.5
        obs, _ = envs.reset()
        envs.step(np.zeros(2, np.int64))
    finally:
        envs.close()

    # sync path: no watchdog (a hang there is the caller thread itself)
    cfg.env.sync_env = True
    sync_envs = vectorize(cfg, [make_env(cfg, 0, 0) for _ in range(2)])
    try:
        assert not isinstance(sync_envs, StepDeadlineVectorEnv)
    finally:
        sync_envs.close()
