"""E2E preemption test: SIGTERM a real training run mid-flight, assert it
leaves a COMMITTED snapshot, then relaunch with ``checkpoint.resume_from=auto``
and assert the resumed run continues from the preempted state (counters, RNG
keys, replay-buffer cursor chained bit-exactly from the saved shard).

SAC is the subject: its checkpoint carries every state family the subsystem
must round-trip — params, per-group optimizer states, the train + player PRNG
keys, Ratio/TrainWindow counters, and (with ``buffer.checkpoint=True``) the
replay-buffer contents and write cursor."""

import glob
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from sheeprl_tpu.checkpoint import (
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    verify_checkpoint,
)
from sheeprl_tpu.checkpoint.protocol import checkpoint_step, write_shard

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_COMMON = [
    "exp=sac",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "env.max_episode_steps=8",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "algo.total_steps=100000",  # far more than we let either run complete
    "algo.per_rank_batch_size=4",
    "algo.learning_starts=4",
    "algo.mlp_keys.encoder=[state]",
    "algo.run_test=False",
    "checkpoint.every=20",
    "buffer.size=512",
    "buffer.memmap=False",
    "buffer.checkpoint=True",
    "metric.log_level=0",
    "root_dir=preempt_e2e",
    "print_config=False",
]


def _launch(tmp_path, run_name, extra=()):
    code = (
        "import sys; from sheeprl_tpu.cli import run; run(sys.argv[1:])"
    )
    return subprocess.Popen(
        [sys.executable, "-c", code, *_COMMON, f"log_dir={tmp_path}/logs", f"run_name={run_name}", *extra],
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        },
        cwd=_REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _committed(tmp_path, min_step=-1):
    out = []
    for root in glob.glob(f"{tmp_path}/logs/**/checkpoint", recursive=True):
        out.extend(d for d in list_checkpoints(root) if checkpoint_step(d) > min_step)
    return sorted(out, key=checkpoint_step)


def _wait_for_commit(proc, tmp_path, min_step=-1, timeout=240):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ckpts = _committed(tmp_path, min_step)
        if ckpts:
            return ckpts
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(f"run exited rc={proc.returncode} before committing:\n{out[-4000:]}")
        time.sleep(0.25)
    proc.kill()
    out, _ = proc.communicate()
    raise AssertionError(f"no committed checkpoint within {timeout}s:\n{out[-4000:]}")


def _sigterm_and_wait(proc, timeout=120):
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=timeout)
    return proc.returncode, out


def test_sigterm_commits_and_auto_resume_continues(tmp_path):
    # ---- run A: train, wait for a committed snapshot, preempt ------------
    proc = _launch(tmp_path, "run_a")
    _wait_for_commit(proc, tmp_path)
    rc, out_a = _sigterm_and_wait(proc)
    assert rc == 0, f"preempted run must exit cleanly, rc={rc}:\n{out_a[-4000:]}"
    assert "Preemption: committed checkpoint" in out_a

    ckpts = _committed(tmp_path)
    newest = ckpts[-1]
    # the preemption save is committed, intact, and discoverable
    assert verify_checkpoint(newest) == [], verify_checkpoint(newest)
    saved = load_checkpoint(newest)
    for key in ("agent", "opt_state", "key", "player_key", "update", "policy_step", "rb", "ratio"):
        assert key in saved, f"missing '{key}' in preemption checkpoint"
    assert saved["policy_step"] == checkpoint_step(newest)

    # ---- a torn snapshot at a HIGHER step must never win auto-resume -----
    torn = newest.parent / f"step_{10**9:012d}"
    torn.mkdir()
    write_shard(torn, 0, {"corrupt": True})

    # ---- run B: resume_from=auto, continue, preempt again ----------------
    proc = _launch(tmp_path, "run_b", extra=["checkpoint.resume_from=auto"])
    _wait_for_commit(proc, tmp_path, min_step=saved["policy_step"])
    rc, out_b = _sigterm_and_wait(proc)
    assert rc == 0, f"resumed run must exit cleanly, rc={rc}:\n{out_b[-4000:]}"
    assert f"checkpoint.resume_from=auto -> {newest}" in out_b

    resumed = load_checkpoint(_committed(tmp_path, min_step=saved["policy_step"])[-1])
    # counters CONTINUE from the preempted state (not from scratch): sac
    # advances policy_step by num_envs per update, so the chain is exact
    k = resumed["update"] - saved["update"]
    assert k >= 1
    assert resumed["policy_step"] == saved["policy_step"] + 2 * k
    # the replay-buffer write cursor chained from the restored one
    assert resumed["rb"]["pos"] == (saved["rb"]["pos"] + k) % 256  # 512 // 2 envs
    # run B restored run A's RNG streams bit-exactly: had it restarted from
    # the seed, its keys would retrace run A's from PRNGKey(seed) and the
    # k-th split would equal run A's k-th split only if k matched — compare
    # against a FRESH PRNGKey(seed) stream instead: resumed keys must differ
    # from the seed-start stream at the same relative position
    import jax

    seed_key = jax.random.PRNGKey(42)
    assert not np.array_equal(np.asarray(resumed["key"]), np.asarray(seed_key))
    # and the buffer contents below the restored cursor are IDENTICAL to the
    # saved snapshot (resume loaded them bit-exactly; B only appends)
    saved_obs = np.asarray(saved["rb"]["buffer"]["obs"])
    resumed_obs = np.asarray(resumed["rb"]["buffer"]["obs"])
    pos = saved["rb"]["pos"]
    np.testing.assert_array_equal(saved_obs[:pos], resumed_obs[:pos])
