"""Unit tests for the fault-tolerant checkpointing subsystem
(sheeprl_tpu/checkpoint/): serialization fidelity (bit-exact round trips,
typed PRNG keys), the durable commit protocol (torn snapshots never
resumable, CRC verification), retention GC, the async writer, preemption
latch, and auto-resume discovery."""

import json
import os
import pickle
import signal
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointManager,
    PREEMPTION_GUARD,
    gc_checkpoints,
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    resolve_auto_resume,
    save_checkpoint,
    verify_checkpoint,
)
from sheeprl_tpu.checkpoint.protocol import (
    checkpoint_step,
    is_committed,
    load_step_dir,
    step_dir_name,
    write_commit,
    write_shard,
)
from sheeprl_tpu.checkpoint.serialize import from_host_tree, to_host_tree
from sheeprl_tpu.utils.structured import dotdict


class _FakeFabric:
    global_rank = 0
    num_processes = 1

    def barrier(self):
        pass


def _cfg(**overrides):
    base = {
        "checkpoint": {
            "every": 1,
            "save_last": True,
            "keep_last": 5,
            "keep_every": None,
            "async_save": True,
            "queue_size": 2,
            "commit_timeout_s": 10.0,
        }
    }
    base["checkpoint"].update(overrides)
    return dotdict(base)


def _rich_state():
    """A state tree exercising every leaf kind the loops checkpoint: jax
    params, an optax opt state, raw uint32 PRNG keys, typed (extended-dtype)
    PRNG keys, numpy buffers, and plain counters."""
    params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
    opt = optax.adam(1e-3)
    return {
        "agent": params,
        "opt_state": opt.init(params),
        "key": jax.random.PRNGKey(7),
        "typed_key": jax.random.key(11),
        "rb": {"buffer": {"obs": np.arange(12, dtype=np.float32).reshape(4, 3)}, "pos": 3},
        "update": 17,
        "policy_step": 340,
    }


def _assert_tree_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------
def test_single_file_roundtrip_bit_exact(tmp_path):
    state = _rich_state()
    save_checkpoint(tmp_path / "c.ckpt", state)
    loaded = load_checkpoint(tmp_path / "c.ckpt")
    # typed PRNG keys must come back as typed keys producing identical streams
    assert jnp.issubdtype(loaded["typed_key"].dtype, jax.dtypes.extended)
    assert jax.random.uniform(loaded["typed_key"]) == jax.random.uniform(state["typed_key"])
    loaded["typed_key"] = jax.random.key_data(loaded["typed_key"])
    state = dict(state)
    state["typed_key"] = jax.random.key_data(state["typed_key"])
    _assert_tree_equal(state, loaded)


def test_host_tree_roundtrip_typed_keys():
    k = jax.random.key(3)
    host = to_host_tree({"k": k})
    # picklable without jax arrays in the stream
    pickle.dumps(host)
    back = from_host_tree(host)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(back["k"])), np.asarray(jax.random.key_data(k))
    )


def test_memmap_missing_backing_file_rehydrates_with_warning(tmp_path):
    from sheeprl_tpu.data.memmap import MemmapArray

    arr = MemmapArray.from_array(np.ones((2, 2), np.float32), filename=tmp_path / "m.memmap")
    blob = pickle.dumps(arr)
    arr.close(delete_file=True)
    assert not os.path.exists(tmp_path / "m.memmap")
    with pytest.warns(RuntimeWarning, match="backing file.*missing"):
        back = pickle.loads(blob)
    assert back.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(back), np.zeros((2, 2), np.float32))


def test_durable_write_leaves_no_tmp(tmp_path):
    from sheeprl_tpu.checkpoint import durable_write

    durable_write(tmp_path / "f.bin", b"payload")
    assert (tmp_path / "f.bin").read_bytes() == b"payload"
    assert [p.name for p in tmp_path.iterdir()] == ["f.bin"]


# ---------------------------------------------------------------------------
# commit protocol
# ---------------------------------------------------------------------------
def test_torn_snapshot_never_selected(tmp_path):
    committed = tmp_path / step_dir_name(100)
    committed.mkdir()
    write_shard(committed, 0, {"update": 1})
    assert write_commit(committed, step=100, world=1)
    # a NEWER but interrupted (uncommitted) snapshot: shard written, no COMMIT
    torn = tmp_path / step_dir_name(200)
    torn.mkdir()
    write_shard(torn, 0, {"update": 2})
    assert latest_checkpoint(tmp_path) == committed
    with pytest.raises(FileNotFoundError, match="torn"):
        load_step_dir(torn)
    assert load_step_dir(committed)["update"] == 1


def test_commit_times_out_without_all_shards(tmp_path):
    d = tmp_path / step_dir_name(10)
    d.mkdir()
    write_shard(d, 0, {"x": 1})
    # world=2 but rank 1 never lands its shard
    assert not write_commit(d, step=10, world=2, timeout_s=0.2)
    assert not is_committed(d)


def test_verify_checkpoint_detects_corruption(tmp_path):
    d = tmp_path / step_dir_name(5)
    d.mkdir()
    write_shard(d, 0, {"x": np.arange(10)})
    write_commit(d, step=5, world=1)
    assert verify_checkpoint(d) == []
    shard = next(d.glob("shard_*.pkl"))
    data = bytearray(shard.read_bytes())
    data[len(data) // 2] ^= 0xFF
    shard.write_bytes(bytes(data))
    problems = verify_checkpoint(d)
    assert problems and "CRC mismatch" in problems[0]


def test_multi_rank_shard_loading_falls_back(tmp_path):
    d = tmp_path / step_dir_name(8)
    d.mkdir()
    write_shard(d, 0, {"rank": 0})
    write_shard(d, 1, {"rank": 1})
    write_commit(d, step=8, world=2)
    assert load_step_dir(d, rank=1)["rank"] == 1
    # resuming with MORE ranks than saved: falls back to shard 0
    assert load_step_dir(d, rank=3)["rank"] == 0


def test_retention_keep_last_plus_keep_every(tmp_path):
    for step in (10, 20, 30, 40, 50):
        d = tmp_path / step_dir_name(step)
        d.mkdir()
        write_shard(d, 0, {"s": step})
        write_commit(d, step=step, world=1)
    deleted = gc_checkpoints(tmp_path, keep_last=2, keep_every=20)
    kept = sorted(checkpoint_step(d) for d in list_checkpoints(tmp_path))
    # keep_last=2 -> {40, 50}; keep_every=20 rescues 20 (and 40, already kept)
    assert kept == [20, 40, 50]
    assert sorted(checkpoint_step(d) for d in deleted) == [10, 30]


def test_retention_removes_stale_torn_snapshots(tmp_path):
    for step in (10, 20):
        d = tmp_path / step_dir_name(step)
        d.mkdir()
        write_shard(d, 0, {"s": step})
        write_commit(d, step=step, world=1)
    torn = tmp_path / step_dir_name(15)
    torn.mkdir()
    write_shard(torn, 0, {"s": 15})
    gc_checkpoints(tmp_path, keep_last=2)
    assert not torn.exists()
    assert len(list_checkpoints(tmp_path)) == 2


# ---------------------------------------------------------------------------
# async writer
# ---------------------------------------------------------------------------
def test_async_writer_executes_jobs_and_flushes():
    w = AsyncCheckpointWriter(queue_size=2)
    done = []
    for i in range(4):
        w.submit(lambda i=i: done.append(i) or 10)
    assert w.flush(timeout_s=10)
    assert done == [0, 1, 2, 3]
    w.close()


def test_async_writer_propagates_errors_on_next_use():
    def boom():
        raise OSError("disk full")

    w = AsyncCheckpointWriter(queue_size=1)
    w.submit(boom)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        w.flush(timeout_s=10)
    # the error is delivered once; the writer keeps working afterwards
    w.submit(lambda: 0)
    assert w.flush(timeout_s=10)
    w.close()


def test_async_writer_backpressure_bounds_queue():
    gate = threading.Event()
    w = AsyncCheckpointWriter(queue_size=1)
    w.submit(lambda: gate.wait(10) and 0)
    t0 = time.monotonic()

    def release():
        time.sleep(0.3)
        gate.set()

    threading.Thread(target=release, daemon=True).start()
    w.submit(lambda: 0)  # queued behind the gated job
    w.submit(lambda: 0)  # must BLOCK until the gate opens
    assert time.monotonic() - t0 >= 0.2
    w.close()


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------
def test_manager_async_save_commits_and_roundtrips(tmp_path):
    mgr = CheckpointManager(_FakeFabric(), _cfg(), tmp_path)
    state = _rich_state()
    mgr.save(340, state)
    mgr.finalize()
    newest = latest_checkpoint(tmp_path / "checkpoint")
    assert newest is not None and checkpoint_step(newest) == 340
    assert verify_checkpoint(newest) == []
    loaded = load_checkpoint(newest)
    assert loaded["update"] == 17
    np.testing.assert_array_equal(np.asarray(loaded["key"]), np.asarray(state["key"]))
    _assert_tree_equal(loaded["agent"], state["agent"])
    _assert_tree_equal(loaded["opt_state"], state["opt_state"])
    assert loaded["rb"]["pos"] == 3


def test_manager_snapshot_isolates_mutating_host_state(tmp_path):
    """The snapshot must capture save-time contents even though the train
    loop keeps writing into the same buffers while the writer serializes."""
    mgr = CheckpointManager(_FakeFabric(), _cfg(), tmp_path)
    buf = np.zeros(8, np.float32)
    mgr.save(1, {"rb": {"buffer": buf}, "policy_step": 1})
    buf[:] = 999.0  # the env loop keeps mutating after submit
    mgr.finalize()
    loaded = load_checkpoint(latest_checkpoint(tmp_path / "checkpoint"))
    np.testing.assert_array_equal(loaded["rb"]["buffer"], np.zeros(8, np.float32))


def test_manager_cadence_and_retention(tmp_path):
    mgr = CheckpointManager(_FakeFabric(), _cfg(every=100, keep_last=2), tmp_path)
    assert not mgr.should_save(policy_step=50, last_checkpoint=0)
    assert mgr.should_save(policy_step=100, last_checkpoint=0)
    assert mgr.should_save(policy_step=50, last_checkpoint=0, final=True)  # save_last
    for step in (100, 200, 300):
        mgr.save(step, {"policy_step": step})
    mgr.finalize()
    kept = [checkpoint_step(d) for d in list_checkpoints(tmp_path / "checkpoint")]
    assert kept == [200, 300]


def test_manager_sync_save_records_metrics(tmp_path):
    from sheeprl_tpu.utils.profiler import CHECKPOINT_MONITOR

    CHECKPOINT_MONITOR.reset()
    mgr = CheckpointManager(_FakeFabric(), _cfg(async_save=False), tmp_path)
    mgr.save(10, {"policy_step": 10, "blob": np.ones(1000, np.float32)})
    m = CHECKPOINT_MONITOR.metrics()
    assert m["Checkpoint/total_saves"] == 1.0
    assert m["Checkpoint/bytes"] > 1000
    assert is_committed(mgr.step_dir(10))


# ---------------------------------------------------------------------------
# preemption + auto-resume
# ---------------------------------------------------------------------------
def test_preemption_guard_latches_and_manager_goes_sync(tmp_path):
    try:
        assert PREEMPTION_GUARD.install()
        assert not PREEMPTION_GUARD.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(100):
            if PREEMPTION_GUARD.requested():
                break
            time.sleep(0.01)
        assert PREEMPTION_GUARD.requested()
        assert PREEMPTION_GUARD.signal_name == "SIGTERM"
        mgr = CheckpointManager(_FakeFabric(), _cfg(every=10**9), tmp_path)
        # preemption overrides cadence AND forces the synchronous path
        assert mgr.should_save(policy_step=1, last_checkpoint=0)
        mgr.save(1, {"policy_step": 1})
        assert is_committed(mgr.step_dir(1))  # no finalize needed: sync
    finally:
        PREEMPTION_GUARD.reset()


def test_resolve_auto_resume_scans_runs_and_skips_torn(tmp_path):
    base, root_dir = tmp_path / "logs", "exp/env"
    runs = base / root_dir
    a = runs / "run_a" / "version_0" / "checkpoint" / step_dir_name(100)
    b = runs / "run_b" / "version_0" / "checkpoint" / step_dir_name(50)
    torn = runs / "run_b" / "version_0" / "checkpoint" / step_dir_name(999)
    for d in (a, b, torn):
        d.mkdir(parents=True)
        write_shard(d, 0, {"s": 1})
    write_commit(a, step=100, world=1)
    time.sleep(0.02)
    write_commit(b, step=50, world=1)  # newest COMMIT wins, even at lower step
    assert resolve_auto_resume(base, root_dir) == b
    assert resolve_auto_resume(base, "nothing/here") is None
