"""The ``fabric.tp_min_param_size`` deprecation path (ISSUE 16 satellite).

PR 7 demoted the knob to parameterizing the legacy ``size_threshold``
fallback table, with a ``DeprecationWarning`` on ``build_fabric``.  Two pins:

* the warning fires ONCE per process, not per call — long runs build
  fabrics repeatedly (supervisor relaunch probes, bench A/B arms, player
  clones), and Python's per-call-site warning dedup does not help a single
  hot callsite (``simplefilter("always")`` below defeats it on purpose:
  the dedup under test is build_fabric's own latch);
* ``sharding.table=size_threshold`` still resolves, and the knob still
  reaches the threshold: a kernel at the threshold shards, one below it
  replicates.
"""

import warnings

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel import fabric as fabric_mod
from sheeprl_tpu.parallel import sharding as shd
from sheeprl_tpu.parallel.fabric import build_fabric


@pytest.fixture
def fresh_latch():
    """Make the test order-independent: the latch is process-wide."""
    old = fabric_mod._TP_MIN_PARAM_SIZE_WARNED
    fabric_mod._TP_MIN_PARAM_SIZE_WARNED = False
    yield
    fabric_mod._TP_MIN_PARAM_SIZE_WARNED = old


def _cfg(*extra):
    return compose([
        "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy",
        "fabric.accelerator=cpu", "fabric.devices=1", *extra,
    ])


def test_tp_min_param_size_warns_exactly_once_per_process(fresh_latch):
    cfg = _cfg("fabric.tp_min_param_size=65536")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build_fabric(cfg)
        build_fabric(cfg)  # supervisor probe / bench second arm / clone
    dep = [w for w in caught if issubclass(w.category, DeprecationWarning)
           and "tp_min_param_size" in str(w.message)]
    assert len(dep) == 1, [str(w.message) for w in caught]
    # and the message points at the replacement surface
    assert "sharding" in str(dep[0].message)


def test_tp_min_param_size_silent_when_unset(fresh_latch):
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build_fabric(_cfg())
    assert not [w for w in caught if "tp_min_param_size" in str(w.message)]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_size_threshold_table_still_resolves_and_honors_knob(fresh_latch):
    cfg = _cfg(
        "fabric.devices=8",
        "fabric.mesh_shape={data: 2, model: 4}",
        "sharding.table=size_threshold",
        "fabric.tp_min_param_size=4096",
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        fabric = build_fabric(cfg)
    rules = fabric.sharding_rules
    assert len(rules) == 1 and rules[0][0] == r".*" and callable(rules[0][1])
    tree = {
        "big/kernel": np.zeros((64, 64), np.float32),     # 4096 = threshold
        "small/kernel": np.zeros((32, 32), np.float32),   # below
    }
    specs = shd.partition_specs(rules, tree, fabric.mesh)
    assert specs["big/kernel"] == P(None, "model")
    assert specs["small/kernel"] == P()
