"""Partition-rules engine units: ordering, first-match-wins, no-match →
replicated, mesh validation, config resolution, explain()."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel import sharding as shd
from sheeprl_tpu.parallel.fabric import Fabric


@pytest.fixture()
def mesh24():
    devs = np.asarray(jax.devices("cpu")[:8]).reshape(2, 4)
    return Mesh(devs, ("data", "model"))


def test_first_match_wins_ordering(mesh24):
    tree = {"block": {"kernel": jnp.zeros((8, 8))}}
    # the specific rule shadows the generic one when listed first...
    specs = shd.match_partition_rules(
        [(r"block/kernel", P("model", None)), (r"kernel", P(None, "model"))], tree
    )
    assert specs["block"]["kernel"] == P("model", None)
    # ...and is shadowed when listed second
    specs = shd.match_partition_rules(
        [(r"kernel", P(None, "model")), (r"block/kernel", P("model", None))], tree
    )
    assert specs["block"]["kernel"] == P(None, "model")


def test_no_match_and_scalars_replicate(mesh24):
    tree = {"bias": jnp.zeros((8,)), "count": jnp.zeros(()), "w": jnp.zeros((8, 8))}
    specs = shd.match_partition_rules([(r"w$", P(None, "model"))], tree)
    assert specs["bias"] == P()
    assert specs["count"] == P()
    assert specs["w"] == P(None, "model")


def test_callable_rule_fallthrough(mesh24):
    def only_big(path, leaf, mesh):
        return P(None, "model") if leaf.size >= 64 else None

    rules = [(r".*", only_big), (r"small", P("data", None))]
    tree = {"big": jnp.zeros((8, 8)), "small": jnp.zeros((4, 4))}
    specs = shd.match_partition_rules(rules, tree, mesh24)
    assert specs["big"] == P(None, "model")
    # the callable declined -> the NEXT rule still gets a chance
    assert specs["small"] == P("data", None)


def test_opt_state_paths_match_param_rules(mesh24):
    """Adam moments carry the kernel path suffix → same spec as the param."""
    import optax

    params = {"trunk": {"dense_0": {"kernel": jnp.zeros((16, 8)), "bias": jnp.zeros((8,))}}}
    opt_state = optax.adam(1e-3).init(params)
    rules = [(r"dense_[0-9]+/kernel", P(None, "model"))]
    pspec = shd.match_partition_rules(rules, params)
    ospec = shd.match_partition_rules(rules, opt_state)
    assert pspec["trunk"]["dense_0"]["kernel"] == P(None, "model")
    flat_o, _ = shd.tree_paths_and_leaves(ospec)
    kernel_specs = [s for p, s in flat_o if p.endswith("dense_0/kernel")]
    assert kernel_specs and all(s == P(None, "model") for s in kernel_specs)
    bias_specs = [s for p, s in flat_o if p.endswith("dense_0/bias")]
    assert bias_specs and all(s == P() for s in bias_specs)


def test_validation_unknown_axis_always_raises(mesh24):
    with pytest.raises(ValueError, match="not in mesh axes"):
        shd.partition_specs(
            [(r"w", P(None, "expert"))], {"w": jnp.zeros((8, 8))}, mesh24
        )


def test_validation_undivisible_policies(mesh24):
    tree = {"w": jnp.zeros((8, 6))}  # 6 % 4 != 0
    rules = [(r"w", P(None, "model"))]
    specs = shd.partition_specs(rules, tree, mesh24, undivisible="replicate")
    assert specs["w"] == P()
    with pytest.raises(ValueError, match="cannot tile"):
        shd.partition_specs(rules, tree, mesh24, undivisible="error")


def test_unmatched_leaves_fully_replicated_on_2d_mesh(mesh24):
    """Satellite check: a small unmatched leaf must land fully replicated
    across the MODEL axis too, not just data — every device holds it."""
    sh = shd.named_sharding_tree(
        mesh24, shd.partition_specs((), {"b": jnp.zeros((3,))}, mesh24)
    )
    x = jax.device_put(jnp.arange(3.0), sh["b"])
    assert x.sharding.is_fully_replicated
    assert len(x.devices()) == 8


def test_dreamer_v3_table_placements(mesh24):
    tree = {
        "world_model": {"params": {
            "recurrent_model": {"gru": {"fused": {"kernel": jnp.zeros((64, 96))}},
                                "in": {"kernel": jnp.zeros((20, 32))},
                                "ln": {"LayerNorm_0": {"scale": jnp.zeros((32,))}}},
            "observation_model": {"cnn_in": {"kernel": jnp.zeros((48, 256))},
                                  "deconv_0": {"kernel": jnp.zeros((4, 4, 16, 8))},
                                  "deconv_out": {"kernel": jnp.zeros((4, 4, 8, 3))},
                                  "head_state": {"kernel": jnp.zeros((32, 7))}},
            "encoder": {"conv_0": {"kernel": jnp.zeros((4, 4, 3, 8))}},
            "initial_recurrent": jnp.zeros((32,)),
        }},
        "actor": {"params": {"trunk": {"dense_0": {"kernel": jnp.zeros((48, 32))}},
                             "head": {"kernel": jnp.zeros((32, 4))}}},
    }
    specs = shd.partition_specs(shd.DREAMER_V3_RULES, tree, mesh24)
    wm = specs["world_model"]["params"]
    assert wm["recurrent_model"]["gru"]["fused"]["kernel"] == P(None, "model")
    assert wm["recurrent_model"]["in"]["kernel"] == P(None, "model")
    assert wm["recurrent_model"]["ln"]["LayerNorm_0"]["scale"] == P()
    assert wm["observation_model"]["cnn_in"]["kernel"] == P(None, "model")
    assert wm["observation_model"]["deconv_0"]["kernel"] == P(None, None, None, "model")
    # RGB output head (3 channels) pinned replicated BEFORE the deconv rule
    assert wm["observation_model"]["deconv_out"]["kernel"] == P()
    # per-key obs head row-shards (7 outputs never divide; 32 inputs do)
    assert wm["observation_model"]["head_state"]["kernel"] == P("model", None)
    assert wm["encoder"]["conv_0"]["kernel"] == P(None, None, None, "model")
    assert wm["initial_recurrent"] == P()
    assert specs["actor"]["params"]["trunk"]["dense_0"]["kernel"] == P(None, "model")
    assert specs["actor"]["params"]["head"]["kernel"] == P("model", None)


def test_resolve_rules_user_rules_prepended():
    rules = shd.resolve_rules(
        {"table": "dreamer_v3", "rules": [["actor/.*kernel", [None, "model"]]]}
    )
    assert rules[0][0] == "actor/.*kernel"
    assert rules[0][1] == P(None, "model")
    assert rules[1:] == shd.DREAMER_V3_RULES
    # the user rule now wins over the table's head rule for actor kernels
    spec, label = shd._match_one(rules, "actor/params/head/kernel", jnp.zeros((8, 8)), None)
    assert spec == P(None, "model") and label == "actor/.*kernel"


def test_resolve_rules_tables():
    assert shd.resolve_rules({"table": "auto", "algo": "dreamer_v3"}) == shd.DREAMER_V3_RULES
    assert shd.resolve_rules({"table": "auto", "algo": "p2e_dv3"}) == shd.DREAMER_V3_RULES
    # no curated table -> size-threshold fallback (one callable catch-all)
    auto = shd.resolve_rules({"table": "auto", "algo": "ppo"}, tp_min_param_size=128)
    assert len(auto) == 1 and callable(auto[0][1])
    assert shd.resolve_rules({"table": "replicate"}) == ()
    with pytest.raises(ValueError, match="Unknown sharding table"):
        shd.resolve_rules({"table": "nope"})


def test_size_threshold_table_matches_legacy_fabric_rule(mesh24):
    """The retired fabric.py ad-hoc rule and its rules-table port place
    every leaf identically (including the divisibility fallback)."""
    rules = shd.size_threshold_rules(64)
    tree = {
        "kernel": jnp.zeros((16, 8)),   # big enough, divides -> sharded
        "bias": jnp.zeros((8,)),        # 1-D -> replicated
        "small": jnp.zeros((4, 4)),     # below threshold -> replicated
        "odd": jnp.zeros((16, 7)),      # 7 % 4 -> replicated (legacy fallback)
    }
    specs = shd.partition_specs(rules, tree, mesh24)
    assert specs["kernel"] == P(None, "model")
    assert specs["bias"] == specs["small"] == specs["odd"] == P()


def test_explain_reports_rule_and_demotion(mesh24):
    tree = {"w": jnp.zeros((8, 8)), "odd": jnp.zeros((8, 6)), "b": jnp.zeros((4,))}
    text = shd.explain(
        [(r"w|odd", P(None, "model"))], tree, mesh24, undivisible="replicate"
    )
    assert "3 leaves, 1 sharded, 1 demoted" in text
    assert "<unmatched>" in text          # b
    assert "does not divide" in text      # odd's demotion reason


def test_fabric_explain_sharding_smoke():
    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4},
                 sharding={"table": "dreamer_v3"})
    text = fab.explain_sharding({"actor": {"params": {"head": {"kernel": jnp.zeros((32, 4))}}}})
    assert "head" in text and "model" in text


def test_shard_batch_divisibility_assertion():
    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4})
    # batch divides the DATA axis only (2), not the whole mesh: fine
    out = fab.shard_batch({"x": jnp.zeros((6, 3))}, axis=0)
    assert out["x"].sharding.spec == P("data", None)
    with pytest.raises(ValueError, match="shard_batch"):
        fab.shard_batch({"x": jnp.zeros((3, 5))}, axis=0)


def test_spec_from_config_forms():
    assert shd.spec_from_config(None) == P()
    assert shd.spec_from_config("model") == P("model")
    assert shd.spec_from_config([None, "model"]) == P(None, "model")
    assert shd.spec_from_config([["data", "model"], None]) == P(("data", "model"), None)


def test_env_state_partition_spec():
    # Anakin env-state placement (envs/jax/anakin.py): leading env axis
    # shards over `data` when divisible, replicates otherwise
    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4})
    assert shd.env_state_partition_spec(4, fab.mesh) == P("data")
    assert shd.env_state_partition_spec(3, fab.mesh) == P()
    assert shd.env_state_partition_spec(4, None) == P()


def test_anakin_actor_state_sharded_over_data():
    import jax
    from sheeprl_tpu.envs.jax.anakin import init_actor_state
    from sheeprl_tpu.envs.jax.cartpole import JaxCartPole
    from sheeprl_tpu.envs.jax.core import VectorJaxEnv

    fab = Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4})
    venv = VectorJaxEnv(JaxCartPole(), 4)
    actor = init_actor_state(fab, venv, jax.random.PRNGKey(0), 0, sharded=True)
    assert actor["env"].x.sharding.spec == P("data")
    assert actor["ep_ret"].sharding.spec == P("data")
    # the update counter replicates (it is a scalar shared by every shard)
    assert actor["update"].sharding.spec == P()
