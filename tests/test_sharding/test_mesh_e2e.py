"""2-D (data, model) mesh end-to-end: the curated dreamer_v3 rule table must
change WHERE state lives without changing WHAT the train step computes.

One seeded DreamerV3-XS train step on a 2x4 data x model CPU mesh (8 fake
devices, conftest.py) vs the same step on a pure-data 8-device mesh:

* losses/params agree within the measured tensor-parallel drift tiers of
  tests/test_parallel/test_tensor_parallel.py (derivation in
  tests/test_regression/DRIFT.md "Tensor-parallel drift" — GSPMD collective
  reassociation noise amplified through near-tie discrete latent samples);
* optimizer-state kernels are sharded exactly like their params (the
  state_io_shardings pin + the shared rule table);
* the program is compile-once: ONE train-phase executable, zero steady-state
  recompiles across repeat dispatches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel import sharding as shd
from sheeprl_tpu.parallel.fabric import build_fabric

TINY = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.id=discrete_dummy",
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=4",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[]",
    # every sharded dim a multiple of 4 so the 2x4 mesh tiles without
    # demotions (the conv channels are the binding constraint)
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=32",
    "algo.world_model.recurrent_model.recurrent_state_size=32",
    "algo.world_model.transition_model.hidden_size=32",
    "algo.world_model.representation_model.hidden_size=32",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "fabric.accelerator=cpu",
    "fabric.devices=8",
    "fabric.precision=32-true",
]


def _one_step(mesh_shape=None, repeats=1):
    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    overrides = list(TINY)
    if mesh_shape:
        overrides.append(f"fabric.mesh_shape={mesh_shape}")
    cfg = compose(overrides)
    fabric = build_fabric(cfg)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
        params=params, opt_state=opt_state,
    )
    rng = np.random.default_rng(0)
    U, L, B = 1, 8, 8
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    block = fabric.shard_batch(block, axis=2)
    params, opt_state, metrics = train_phase(
        params, opt_state, block, jax.random.PRNGKey(3), jnp.int32(0)
    )
    for i in range(1, repeats):
        params, opt_state, metrics = train_phase(
            params, opt_state, block, jax.random.PRNGKey(3), jnp.int32(i)
        )
    jax.block_until_ready(metrics)
    return fabric, train_phase, params, opt_state, jax.device_get(metrics)


def _paths_and_specs(tree):
    flat, _ = shd.tree_paths_and_leaves(tree)
    return {p: l.sharding.spec for p, l in flat if isinstance(l, jax.Array)}


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dv3_2x4_mesh_loss_parity_and_opt_sharding():
    fab, train_phase, params, opt_state, m_tp = _one_step(
        "{data: 2, model: 4}", repeats=2
    )
    assert fab.model_axis == "model" and dict(fab.mesh.shape) == {"data": 2, "model": 4}

    # the curated table actually sharded the model: RSSM + actor/critic
    pspecs = _paths_and_specs(params)
    sharded = {p: s for p, s in pspecs.items() if any(e is not None for e in s)}
    assert any("recurrent_model/gru/fused/kernel" in p for p in sharded)
    assert any("actor" in p and "dense_0/kernel" in p for p in sharded)
    assert pspecs["actor/params/head/kernel"] == P("model", None)

    # opt-state kernels sharded EXACTLY like their params (state pinning):
    # every param kernel's spec appears on its mu/nu moments
    ospecs = _paths_and_specs(opt_state)
    matched = 0
    # target_critic is EMA-updated, not optimized: no moments to check
    optimized = {p: s for p, s in sharded.items() if not p.startswith("target_critic")}
    for opath, ospec in ospecs.items():
        for ppath, pspec in optimized.items():
            # param path world_model/params/X -> opt path world_model/../(mu|nu)/params/X
            group, suffix = ppath.split("/", 1)
            if opath.startswith(group) and opath.endswith(suffix) and (
                "/mu/" in opath or "/nu/" in opath
            ):
                assert ospec == pspec, (opath, ospec, pspec)
                matched += 1
    assert matched == 2 * len(optimized)  # one mu + one nu per sharded kernel

    # compile-once under TP: repeat dispatches hit ONE executable
    assert train_phase.cache_size() == 1

    # loss parity vs the pure-data mesh, within the measured TP drift tiers
    # (tests/test_parallel/test_tensor_parallel.py, DRIFT.md)
    _, _, p_dp, _, m_dp = _one_step(None, repeats=2)
    for a, b in zip(jax.tree_util.tree_leaves(m_tp), jax.tree_util.tree_leaves(m_dp)):
        b_arr = np.asarray(b)
        rtol = 1e-2 if np.all(np.abs(b_arr) > 10) else 1e-1
        np.testing.assert_allclose(np.asarray(a), b_arr, rtol=rtol, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(p_dp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-3
        )


@pytest.mark.slow
def test_dv3_xlplus_500m_dryrun_2d_mesh():
    """ISSUE 7 acceptance: the 500M+ XL+ preset trains one step on an
    8-fake-device 2-D mesh with opt state sharded like params.  ~500M fp32
    params + Adam moments => >6 GiB of host RAM and a multi-minute XLA
    compile on small hosts — slow-marked, excluded from tier-1."""
    import os

    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    cfg = compose([
        "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy", "algo=dreamer_v3_XL+",
        "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]", "algo.horizon=4",
        "fabric.accelerator=cpu", "fabric.devices=8",
        "fabric.mesh_shape={data: 2, model: 4}",
        # every sharded dim must tile the 500M preset cleanly: demotion = bug
        "sharding.undivisible=error",
    ])
    fabric = build_fabric(cfg)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    n_wm = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params["world_model"]))
    assert n_wm >= 500_000_000, f"XL+ world model is {n_wm / 1e6:.0f}M params, expected 500M+"
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
        params=params, opt_state=opt_state,
    )
    rng = np.random.default_rng(0)
    U, L, B = 1, 2, 2
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    block = fabric.shard_batch(block, axis=2)
    params, opt_state, metrics = train_phase(
        params, opt_state, block, jax.random.PRNGKey(0), jnp.int32(0)
    )
    jax.block_until_ready(metrics)
    assert np.isfinite(float(np.asarray(metrics[0])))
    # zero steady-state recompiles: the one executable serves a second step
    params, opt_state, metrics = train_phase(
        params, opt_state, block, jax.random.PRNGKey(0), jnp.int32(1)
    )
    jax.block_until_ready(metrics)
    assert train_phase.cache_size() == 1
