#!/usr/bin/env python
"""run_ci stage 18: the PBT-beats-fixed-hyperparams drill (ISSUE 20).

Two seeded population=4 CartPole PPO runs at EQUAL env steps through the
real CLI, differing in exactly one knob:

* **pbt** — in-trace exploit/explore armed (``population.exploit_every``):
  truncation selection copies the top member's params+opt-state over the
  bottom member's and perturbs its hyperparams, inside the ONE fused
  executable (``algo.max_recompiles=1`` + the armed transfer guard gate
  the compile-once / zero-H2D law the whole time);
* **fixed** — ``population.exploit_every=0``: the same seeded log-uniform
  hyperparameter spread, trained to the end with no selection — the
  classic fixed-hyperparam control arm.

Gate: the PBT arm's best member must beat the fixed arm's WORST member on
final fitness (the episode-return EMA from the fused carry).  That is the
minimal honest claim PBT makes — selection reallocates the budget of the
doomed members — and it must hold at this tiny scale for the subsystem to
be worth its complexity.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python tests/population_drill.py` without an install
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG_ROOT = "/tmp/run_ci_population"

COMMON = [
    "exp=ppo",
    "env=jax_cartpole",
    "env.num_envs=4",
    "seed=42",
    "algo.rollout_steps=32",
    "algo.per_rank_batch_size=32",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.total_steps=40000",
    "algo.max_recompiles=1",
    "algo.run_test=False",
    "population.size=4",
    # a wide seeded init spread: the doomed members are REALLY doomed
    # (lr down to 0.05x base), so selection has signal to act on
    "population.init_min=0.05",
    "population.init_max=2.0",
    "population.warmup=8",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "env.sync_env=True",
    "env.capture_video=False",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "metric.log_level=1",
    "metric.log_every=5000",
    "print_config=False",
]


def _summary(log_dir: str) -> dict:
    hits = glob.glob(os.path.join(log_dir, "**", "population_summary.json"), recursive=True)
    assert len(hits) == 1, f"expected one population_summary.json under {log_dir}, got {hits}"
    with open(hits[0]) as f:
        return json.load(f)


def main() -> int:
    from sheeprl_tpu.utils.utils import force_cpu_backend

    force_cpu_backend()
    from sheeprl_tpu.cli import run

    shutil.rmtree(LOG_ROOT, ignore_errors=True)

    arms = {
        "pbt": ["population.exploit_every=8"],
        "fixed": ["population.exploit_every=0"],
    }
    results = {}
    for name, extra in arms.items():
        log_dir = os.path.join(LOG_ROOT, name)
        run([*COMMON, *extra, f"log_dir={log_dir}"])
        results[name] = _summary(log_dir)
        print(
            f"[population_drill] {name}: fitness={['%.1f' % f for f in results[name]['fitness']]} "
            f"exploits={results[name]['exploit_events']}"
        )

    pbt, fixed = results["pbt"], results["fixed"]
    # sanity: the control arm really was selection-free, the PBT arm wasn't
    assert fixed["exploit_events"] == 0, f"control arm exploited: {fixed['exploit_events']}"
    assert pbt["exploit_events"] > 0, "PBT arm never exploited — cadence/warmup misconfigured"
    # both arms completed identical member episodes budgets (equal env steps
    # is by construction: same total_steps, same population size)
    assert pbt["best_fitness"] > fixed["worst_fitness"], (
        f"PBT best member ({pbt['best_fitness']:.2f}) failed to beat the worst "
        f"fixed-hyperparam member ({fixed['worst_fitness']:.2f})"
    )
    print(
        f"population drill OK: PBT best {pbt['best_fitness']:.1f} > "
        f"fixed worst {fixed['worst_fitness']:.1f} at equal env steps "
        f"({pbt['exploit_events']} exploit events)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
