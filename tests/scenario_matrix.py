#!/usr/bin/env python
"""Scenario-matrix CI: every algo × {cpu-gym, jax-env, dummy} × {coupled,
decoupled} dryrun grid with per-cell wall and compile budgets (ROADMAP item
5 / ISSUE 11) — "as many scenarios as you can imagine" as an enforced gate
instead of a slogan.

Each cell is an end-to-end dryrun through ``sheeprl_tpu.cli.run`` on tiny
shapes with ``algo.max_recompiles=1`` (the recompile detector is the
compile budget: any program whose signature churns dies red) and a wall
budget per cell (a wedged cell fails the grid; the run_ci stage timeout
backstops a hang).  Cells a family cannot express are PRUNED with an
explicit reason (e.g. sac_ae needs pixel obs — classic-control gym/jax
envs have none), so the printed table documents the coverage honestly.

Extra jax-env cells pin both rollout modes of the on-policy loops: Anakin
fused (``algo.anakin=auto`` resolves on) AND the JaxToGymAdapter fallback
(``algo.anakin=False``).  The sebulba rows (ISSUE 12) drive the decoupled
algos through the actor–learner device split on a 2-fake-device
1-actor/1-learner topology, for ppo/sac × {cpu-gym, jax-env}.

Usage:
  python tests/scenario_matrix.py              # full grid (run_ci stage)
  python tests/scenario_matrix.py --filter ppo # substring-matched subset
  SCENARIO_FILTER=jax python tests/scenario_matrix.py
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import traceback
from typing import List, Optional, Tuple

# must precede any jax import (conftest-equivalent for a plain script);
# the sebulba cells need >= 2 fake devices for a real actor/learner split
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
# runnable as `python tests/scenario_matrix.py` without an install
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COMMON = [
    "dry_run=True",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "metric.log_level=0",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "print_config=False",
    "algo.run_test=False",
    "algo.max_recompiles=1",  # the per-cell COMPILE budget
]

# tiny world-model sizing shared by the dreamer family (mirrors
# tests/test_algos.TINY_WM_ARGS minus the obs-key choices, which are
# per-family here)
TINY_WM = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.learning_starts=0",
    "algo.horizon=4",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.mlp_layers=1",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
    "buffer.size=400",
]
TINY_DV23 = ["algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4"]
TINY_ONPOLICY = [
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
]
TINY_SAC = [
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=4",
    "algo.mlp_keys.encoder=[state]",
    "buffer.size=64",
]

# env-family fragments, keyed by the action-space class an algo needs
FAMILY_ENVS = {
    "dummy": {
        "discrete": ["env=dummy", "env.id=discrete_dummy", "env.max_episode_steps=16"],
        "continuous": ["env=dummy", "env.id=continuous_dummy", "env.max_episode_steps=16"],
    },
    "cpu_gym": {
        "discrete": ["env=gym", "env.id=CartPole-v1", "env.sync_env=True"],
        "continuous": ["env=gym", "env.id=Pendulum-v1", "env.sync_env=True"],
    },
    "jax": {
        "discrete": ["env=jax_cartpole"],
        "continuous": ["env=jax_pendulum"],
    },
}

# obs-key fragments: dummy envs expose rgb+state; the classic-control
# gym/jax envs are state-only
KEYS_PIXEL_STATE = ["algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[state]"]
KEYS_STATE_ONLY = ["algo.cnn_keys.encoder=[]", "algo.mlp_keys.encoder=[state]"]

Cell = Tuple[str, Optional[List[str]], str, float]  # (name, overrides|None, skip_reason, budget_s)


def _dreamer(exp: str, family: str, extra: List[str], space: str = "discrete", budget: float = 360.0) -> Cell:
    env = FAMILY_ENVS[family][space]
    keys = KEYS_PIXEL_STATE if family == "dummy" else KEYS_STATE_ONLY
    return (
        f"{exp}×{family}×coupled",
        [f"exp={exp}", *env, *keys, *TINY_WM, *extra],
        "",
        budget,
    )


def build_cells() -> List[Cell]:
    cells: List[Cell] = []
    families = ("dummy", "cpu_gym", "jax")

    # ---- on-policy (coupled): the jax column exercises ANAKIN fusion ----
    for exp in ("ppo", "a2c", "ppo_recurrent"):
        extra = ["algo.update_epochs=1"] if exp == "ppo" else []
        if exp == "ppo_recurrent":
            extra = ["algo.update_epochs=1", "algo.per_rank_sequence_length=4"]
        for fam in families:
            fam_extra = list(extra)
            if exp == "ppo_recurrent" and fam != "cpu_gym":
                # the exp config masks CartPole velocities; the masking
                # wrapper only knows the gym classic-control layouts
                fam_extra.append("env.mask_velocities=False")
            cells.append(
                (
                    f"{exp}×{fam}×coupled",
                    [f"exp={exp}", *FAMILY_ENVS[fam]["discrete"], *TINY_ONPOLICY, *fam_extra],
                    "",
                    240.0,
                )
            )
    # both rollout modes of the fused loops are load-bearing: pin the
    # adapter fallback and the pixel (CNN) fused path explicitly
    cells.append(
        (
            "ppo×jax×coupled-adapter",
            ["exp=ppo", *FAMILY_ENVS["jax"]["discrete"], *TINY_ONPOLICY,
             "algo.update_epochs=1", "algo.anakin=False"],
            "",
            240.0,
        )
    )
    cells.append(
        (
            "ppo×jax_forage×coupled-anakin-cnn",
            ["exp=ppo", "env=jax_forage", "algo.rollout_steps=4",
             "algo.per_rank_batch_size=8", "algo.update_epochs=1",
             "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]"],
            "",
            300.0,
        )
    )
    # the procedural multi-room world (docs/jax_envs.md) in BOTH rollout
    # modes; the anakin cell also pins the env.level difficulty override
    # reaching the fused in-trace layout generator
    cells.append(
        (
            "ppo×jax_multiroom×coupled-anakin-cnn",
            ["exp=ppo", "env=jax_multiroom", "env.level=1.0",
             "algo.rollout_steps=4", "algo.per_rank_batch_size=8",
             "algo.update_epochs=1",
             "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]"],
            "",
            300.0,
        )
    )
    cells.append(
        (
            "ppo×jax_multiroom×coupled-adapter",
            ["exp=ppo", "env=jax_multiroom", "algo.anakin=False",
             "algo.rollout_steps=4", "algo.per_rank_batch_size=8",
             "algo.update_epochs=1",
             "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]"],
            "",
            300.0,
        )
    )

    # ---- off-policy (coupled) ----
    for fam in families:
        cells.append(
            (
                f"sac×{fam}×coupled",
                ["exp=sac", *FAMILY_ENVS[fam]["continuous"], *TINY_SAC],
                "",
                240.0,
            )
        )
        cells.append(
            (
                f"droq×{fam}×coupled",
                ["exp=droq", *FAMILY_ENVS[fam]["continuous"], *TINY_SAC],
                "",
                240.0,
            )
        )
        if fam == "dummy":
            cells.append(
                (
                    f"sac_ae×{fam}×coupled",
                    ["exp=sac_ae", *FAMILY_ENVS[fam]["continuous"],
                     "algo.per_rank_batch_size=4", "algo.learning_starts=4",
                     "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[state]",
                     "algo.cnn_channels_multiplier=4", "algo.hidden_size=32",
                     "algo.encoder.features_dim=16", "env.screen_size=32",
                     "buffer.size=64"],
                    "",
                    300.0,
                )
            )
        else:
            cells.append(
                (f"sac_ae×{fam}×coupled", None,
                 "sac_ae needs pixel obs; classic-control gym/jax envs are state-only", 0.0)
            )

    # ---- dreamer family (coupled) ----
    for fam in families:
        cells.append(_dreamer("dreamer_v1", fam, ["algo.world_model.stochastic_size=8"], space="continuous"))
        cells.append(_dreamer("dreamer_v2", fam, TINY_DV23))
        cells.append(_dreamer("dreamer_v3", fam, TINY_DV23))
        cells.append(
            _dreamer("p2e_dv3_exploration", fam, [*TINY_DV23, "algo.ensembles.n=2"], budget=420.0)
        )
        # p2e_dv1/dv2 exploration share the dv1/dv2 world-model stacks the
        # rows above already drive per family; finetuning variants need an
        # exploration checkpoint and cannot dryrun standalone
        for exp in ("p2e_dv1_exploration", "p2e_dv2_exploration"):
            if fam == "dummy":
                extra = ["algo.ensembles.n=2", "algo.per_rank_pretrain_steps=0"]
                extra += TINY_DV23 if exp.endswith("dv2_exploration") else ["algo.world_model.stochastic_size=8"]
                cells.append(_dreamer(exp, fam, extra, space="continuous", budget=420.0))
            else:
                cells.append(
                    (f"{exp}×{fam}×coupled", None,
                     "world-model stack covered by the dv1/dv2 rows; one ensemble cell per algo", 0.0)
                )
    for fam in families:
        for exp in ("p2e_dv1_finetuning", "p2e_dv2_finetuning", "p2e_dv3_finetuning"):
            cells.append(
                (f"{exp}×{fam}×coupled", None,
                 "finetuning resumes an exploration checkpoint; no standalone dryrun", 0.0)
            )

    # ---- decoupled topologies ----
    for fam in families:
        cells.append(
            (
                f"ppo_decoupled×{fam}×decoupled",
                ["exp=ppo_decoupled", *FAMILY_ENVS[fam]["discrete"], *TINY_ONPOLICY,
                 "algo.update_epochs=1"],
                "",
                300.0,
            )
        )
        cells.append(
            (
                f"sac_decoupled×{fam}×decoupled",
                ["exp=sac_decoupled", *FAMILY_ENVS[fam]["continuous"], *TINY_SAC],
                "",
                300.0,
            )
        )

    # ---- sebulba device-split topology (ISSUE 12) ----
    # cpu-gym cells drive the env-worker + batched-AOT-inference path,
    # jax cells the fused on-device rollout shards (ppo) and the
    # jax-through-adapter worker path (sac); every cell is a real 1-actor/
    # 1-learner device split on 2 fake devices
    SEBULBA = ["topology=sebulba", "topology.env_workers=2",
               "fabric.devices=2", "env.num_envs=2"]
    for fam in ("cpu_gym", "jax"):
        cells.append(
            (
                f"ppo_decoupled×{fam}×sebulba",
                ["exp=ppo_decoupled", *FAMILY_ENVS[fam]["discrete"], *TINY_ONPOLICY,
                 "algo.update_epochs=1", *SEBULBA],
                "",
                300.0,
            )
        )
        cells.append(
            (
                f"sac_decoupled×{fam}×sebulba",
                ["exp=sac_decoupled", *FAMILY_ENVS[fam]["continuous"], *TINY_SAC,
                 *SEBULBA, "topology.segment_steps=4"],
                "",
                300.0,
            )
        )
    return cells


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--filter", default=os.environ.get("SCENARIO_FILTER", ""),
                        help="substring filter on cell names")
    parser.add_argument("--list", action="store_true", help="print the grid and exit")
    args = parser.parse_args()

    cells = build_cells()
    if args.filter:
        cells = [c for c in cells if args.filter in c[0]]
    if args.list:
        for name, overrides, reason, budget in cells:
            print(f"{name:48s} {'SKIP: ' + reason if overrides is None else f'budget {budget:.0f}s'}")
        return 0

    from sheeprl_tpu.utils.utils import force_cpu_backend

    force_cpu_backend()
    from sheeprl_tpu.cli import run

    results = []
    failures = []
    logroot = os.environ.get("SCENARIO_LOG_DIR", "/tmp/scenario_matrix")
    for idx, (name, overrides, reason, budget) in enumerate(cells):
        if overrides is None:
            results.append((name, "skip", 0.0, reason))
            continue
        t0 = time.perf_counter()
        try:
            # COMMON first: cells may override it (the sebulba cells need a
            # real 2-device split over COMMON's fabric.devices=1)
            run([*COMMON, *overrides, f"log_dir={logroot}/{idx}"])
            wall = time.perf_counter() - t0
            if wall > budget:
                results.append((name, "OVER-BUDGET", wall, f"> {budget:.0f}s"))
                failures.append(name)
            else:
                results.append((name, "ok", wall, ""))
        except Exception:
            wall = time.perf_counter() - t0
            results.append((name, "FAIL", wall, traceback.format_exc(limit=3).splitlines()[-1]))
            failures.append(name)
            traceback.print_exc()

    ran = sum(1 for r in results if r[1] == "ok")
    skipped = sum(1 for r in results if r[1] == "skip")
    print("\n=== scenario matrix ===")
    for name, status, wall, note in results:
        line = f"{name:48s} {status:12s} {wall:7.1f}s"
        if note:
            line += f"  {note}"
        print(line)
    print(f"\n{ran} ok, {skipped} pruned, {len(failures)} failed of {len(results)} cells")
    if failures:
        print("FAILED cells:", ", ".join(failures))
        return 1
    print("scenario matrix: ALL GREEN")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
