"""Fused RSSM recurrent-path Pallas kernel vs the flax RecurrentModel
(interpret mode, no TPU needed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.dreamer_v3.agent import RecurrentModel
from sheeprl_tpu.ops.rssm_pallas import fused_rssm_recurrent


def _flax_reference(B=6, ZA=20, D=16, H=24, seed=0):
    model = RecurrentModel(recurrent_size=H, dense_units=D)
    key = jax.random.PRNGKey(seed)
    h0 = jax.random.normal(key, (B, H))
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, ZA))
    params = model.init(jax.random.fold_in(key, 2), h0, x)
    ref = np.asarray(model.apply(params, h0, x))
    p = params["params"]
    w_in = p["in"]["kernel"]
    b_in = p["in"]["bias"]
    ln = p["ln"]["LayerNorm_0"]
    w_gru = p["gru"]["fused"]["kernel"]
    gru_ln = p["gru"]["ln"]["LayerNorm_0"]
    return (
        x, h0,
        (w_in, b_in, ln["scale"], ln["bias"], w_gru, gru_ln["scale"], gru_ln["bias"]),
        ref,
    )


def test_fused_rssm_matches_flax_path():
    x, h0, weights, ref = _flax_reference()
    out = fused_rssm_recurrent(x, h0, *weights, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_fused_rssm_batch_padding():
    x, h0, weights, ref = _flax_reference(B=5)
    out = fused_rssm_recurrent(x, h0, *weights, block_b=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_fused_rssm_under_scan():
    x, h0, weights, _ = _flax_reference()

    def step(h, x_t):
        h = fused_rssm_recurrent(x_t, h, *weights, interpret=True)
        return h, h

    xs = jnp.stack([x, x * 0.5, -x])
    final, seq = jax.lax.scan(step, h0, xs)
    assert seq.shape == (3, *h0.shape)
    assert np.isfinite(np.asarray(final)).all()


def test_fused_pallas_module_flag_runs_end_to_end():
    """RecurrentModel(fused_pallas=True) declares flat params and produces
    finite states of the right shape (its own layout — not checkpoint-
    compatible with the flax path, by documented design)."""
    model = RecurrentModel(recurrent_size=24, dense_units=16, fused_pallas=True)
    key = jax.random.PRNGKey(0)
    h0 = jax.random.normal(key, (6, 24))
    x = jax.random.normal(jax.random.fold_in(key, 1), (6, 20))
    params = model.init(jax.random.fold_in(key, 2), h0, x)
    assert "in_kernel" in params["params"] and "gru_kernel" in params["params"]
    out = model.apply(params, h0, x)
    assert out.shape == (6, 24)
    assert np.isfinite(np.asarray(out)).all()


def test_fused_rssm_gradients_match_flax():
    """The kernel must be differentiable (training scans grad through it):
    custom_vjp backward = XLA autodiff of the same math."""
    x, h0, weights, _ = _flax_reference()

    def loss_fused(x, h, *w):
        return jnp.sum(fused_rssm_recurrent(x, h, *w, interpret=True) ** 2)

    from sheeprl_tpu.ops.rssm_pallas import _reference_math

    def loss_ref(x, h, *w):
        return jnp.sum(_reference_math(x, h, *w) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 6))(x, h0, *weights)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 6))(x, h0, *weights)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_tiled_rssm_matches_flax_path_L_preset():
    """The H-tiled streamed kernel (M/L/XL presets, w_gru > VMEM budget) must
    match the flax path at REAL L-preset dims (D=768, H=2048 ⇒ w_gru ≈ 69 MB
    fp32 — forced through _pallas_forward_tiled by the size dispatch)."""
    x, h0, weights, ref = _flax_reference(B=4, ZA=1030, D=768, H=2048)
    out = fused_rssm_recurrent(x, h0, *weights, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_tiled_rssm_forced_small():
    """Tiled kernel correctness independent of the size dispatch: run it
    directly at small dims (multiple batch tiles + multiple column tiles +
    batch padding) against the pure-math reference."""
    from sheeprl_tpu.ops.rssm_pallas import _pallas_forward_tiled, _reference_math

    x, h0, weights, ref = _flax_reference(B=11, ZA=20, D=256, H=512)
    # 3H=1536 ⇒ three 512-wide column tiles; B=11, block_b=4 ⇒ padded batch tiles
    out = _pallas_forward_tiled(x, h0, *weights, block_b=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_tiled_vmem_planner_fits_all_presets():
    """ADVICE r3: the tiled path must account its VMEM working set.  Every
    Dreamer preset (S..XL, reference agent.py world-model sizes) must admit
    a legal tiling within the budget."""
    from sheeprl_tpu.ops.rssm_pallas import (
        _VMEM_WEIGHT_BUDGET_BYTES,
        _plan_tiled,
        _tiled_vmem_bytes,
    )

    # (dense_units D, recurrent H); ZA ~ stoch_flat + actions
    presets = {"S": (512, 512), "M": (640, 1024), "L": (768, 2048), "XL": (1024, 4096)}
    for name, (D, H) in presets.items():
        ZA = 32 * 32 + 6
        bt, tj = _plan_tiled(64, ZA, D, H, block_b=64)
        assert (3 * H) % tj == 0, name
        got = _tiled_vmem_bytes(bt, tj, ZA, D, H)
        assert got <= _VMEM_WEIGHT_BUDGET_BYTES, (
            f"{name}: planned tiling (bt={bt}, tj={tj}) still needs {got / 2**20:.1f} MiB"
        )


def test_tiled_vmem_planner_rejects_absurd_model():
    from sheeprl_tpu.ops.rssm_pallas import _plan_tiled

    with pytest.raises(ValueError, match="cannot fit VMEM"):
        _plan_tiled(64, 65536, 32768, 32768, block_b=64)


def test_tp_model_axis_rejects_pallas_rssm():
    """TP column-shards w_gru; the pallas_call path must refuse loudly
    (ADVICE r3) instead of silently all-gathering or failing in Mosaic."""
    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import Fabric

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo=dreamer_v3_XS",
            "fabric.devices=2",
            "fabric.accelerator=cpu",
            "algo.world_model.recurrent_model.fused_pallas=True",
            "algo.cnn_keys.encoder=[]",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    fabric = Fabric(
        devices=2, accelerator="cpu", precision="32-true",
        mesh_shape={"data": -1, "model": 2},
    )
    obs_space = spaces.Dict({"state": spaces.Box(-1, 1, (4,), np.float32)})
    with pytest.raises(ValueError, match="cannot be[\\s\\S]*combined with the Pallas"):
        build_agent(fabric, (4,), False, cfg, obs_space)
