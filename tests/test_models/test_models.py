import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.models import (
    CNN,
    DeCNN,
    LayerNorm,
    LayerNormGRUCell,
    MLP,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    cnn_forward,
)

KEY = jax.random.PRNGKey(0)


def test_mlp_shapes_and_head():
    m = MLP(hidden_sizes=(32, 32), output_dim=5, layer_norm=True)
    params = m.init(KEY, jnp.ones((4, 10)))
    out = m.apply(params, jnp.ones((4, 10)))
    assert out.shape == (4, 5)


def test_mlp_bf16_compute_fp32_params():
    m = MLP(hidden_sizes=(16,), output_dim=2, dtype=jnp.bfloat16)
    params = m.init(KEY, jnp.ones((2, 8)))
    leaf = jax.tree.leaves(params)[0]
    assert leaf.dtype == jnp.float32
    out = m.apply(params, jnp.ones((2, 8)))
    assert out.dtype == jnp.bfloat16


def test_cnn_nhwc():
    m = CNN(channels=(16, 32), kernel_sizes=4, strides=2)
    x = jnp.ones((2, 64, 64, 3))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.ndim == 2 and out.shape[0] == 2


def test_decnn_upsamples():
    m = DeCNN(channels=(16, 3), kernel_sizes=4, strides=2)
    x = jnp.ones((2, 8, 8, 32))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (2, 32, 32, 3)


def test_nature_cnn_output_dim():
    m = NatureCNN(features_dim=512)
    x = jnp.ones((3, 64, 64, 4))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (3, 512)


def test_layernorm_gru_cell_step_and_scan():
    cell = LayerNormGRUCell(units=32)
    h0 = LayerNormGRUCell.initial_state(4, 32)
    x = jnp.ones((4, 16))
    params = cell.init(KEY, h0, x)
    h1, _ = cell.apply(params, h0, x)
    assert h1.shape == (4, 32)
    assert not np.allclose(np.asarray(h1), 0)

    # scan over time with the same params
    xs = jnp.ones((10, 4, 16))

    def step(h, x_t):
        h, _ = cell.apply(params, h, x_t)
        return h, h

    hT, hs = jax.lax.scan(step, h0, xs)
    assert hs.shape == (10, 4, 32)
    np.testing.assert_allclose(np.asarray(hs[-1]), np.asarray(hT))


def test_multi_encoder_fuses_keys():
    enc = MultiEncoder(
        cnn_keys=("rgb",), mlp_keys=("state",), cnn_channels=(8, 16), mlp_sizes=(32,)
    )
    obs = {"rgb": jnp.ones((2, 64, 64, 3)), "state": jnp.ones((2, 7))}
    params = enc.init(KEY, obs)
    out = enc.apply(params, obs)
    assert out.ndim == 2 and out.shape[0] == 2


def test_multi_encoder_requires_keys():
    enc = MultiEncoder(cnn_keys=(), mlp_keys=())
    with pytest.raises(ValueError):
        enc.init(KEY, {})


def test_multi_decoder_reconstructs_per_key():
    dec = MultiDecoder(
        cnn_keys=("rgb", "depth"),
        mlp_keys=("state",),
        cnn_shapes={"rgb": (32, 32, 3), "depth": (32, 32, 1)},
        mlp_shapes={"state": 7},
        cnn_channels=(16, 8),
        cnn_stem_channels=32,
        mlp_sizes=(16,),
    )
    feats = jnp.ones((2, 64))
    params = dec.init(KEY, feats)
    out = dec.apply(params, feats)
    assert set(out) == {"rgb", "depth", "state"}
    assert out["rgb"].shape == (2, 32, 32, 3)
    assert out["depth"].shape == (2, 32, 32, 1)
    assert out["state"].shape == (2, 7)
    # heads stay fp32 under bf16 compute (loss-side numerics policy)
    dec16 = dec.copy(dtype=jnp.bfloat16)
    out16 = dec16.apply(dec16.init(KEY, feats), feats)
    assert out16["state"].dtype == jnp.float32


def test_multi_decoder_leading_time_batch_dims():
    dec = MultiDecoder(
        cnn_keys=("rgb",),
        mlp_keys=(),
        cnn_shapes={"rgb": (16, 16, 3)},
        cnn_channels=(8,),
        cnn_stem_channels=16,
    )
    feats = jnp.ones((5, 2, 32))  # (T, B, F)
    params = dec.init(KEY, feats)
    out = dec.apply(params, feats)
    assert out["rgb"].shape == (5, 2, 16, 16, 3)


def test_multi_decoder_requires_keys():
    dec = MultiDecoder(cnn_keys=(), mlp_keys=())
    with pytest.raises(ValueError):
        dec.init(KEY, jnp.ones((2, 8)))


def test_cnn_forward_tb_adapter():
    m = NatureCNN(features_dim=64)
    x = jnp.ones((5, 2, 64, 64, 3))  # (T, B, H, W, C)
    params = m.init(KEY, x.reshape(-1, 64, 64, 3))
    out = cnn_forward(lambda img: m.apply(params, img), x)
    assert out.shape == (5, 2, 64)


def test_layernorm_dtype_preserved():
    ln = LayerNorm(dtype=jnp.bfloat16)
    x = jnp.ones((2, 8), jnp.bfloat16)
    params = ln.init(KEY, x)
    out = ln.apply(params, x)
    assert out.dtype == jnp.bfloat16
