"""Pallas fused LayerNorm-GRU vs the flax cell (interpret mode, no TPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import LayerNormGRUCell
from sheeprl_tpu.ops.gru_pallas import fused_layernorm_gru


def _flax_reference(B=12, D=24, H=32, seed=0):
    cell = LayerNormGRUCell(units=H, layer_norm=True)
    key = jax.random.PRNGKey(seed)
    h0 = jax.random.normal(key, (B, H))
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, D))
    params = cell.init(jax.random.fold_in(key, 2), h0, x)
    ref_out, _ = cell.apply(params, h0, x)
    inner = params["params"]
    w = inner["fused"]["kernel"]
    ln = inner["ln"]["LayerNorm_0"]
    return x, h0, w, ln["scale"], ln["bias"], np.asarray(ref_out)


def test_fused_gru_matches_flax_cell():
    x, h0, w, scale, bias, ref = _flax_reference()
    out = fused_layernorm_gru(x, h0, w, scale, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_fused_gru_batch_padding():
    # batch not a multiple of the tile → padded path
    x, h0, w, scale, bias, ref = _flax_reference(B=5)
    out = fused_layernorm_gru(x, h0, w, scale, bias, block_b=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_fused_gru_under_scan():
    x, h0, w, scale, bias, _ = _flax_reference()

    def step(h, x_t):
        h = fused_layernorm_gru(x_t, h, w, scale, bias, interpret=True)
        return h, h

    xs = jnp.stack([x] * 4)
    hT, hs = jax.lax.scan(step, jnp.asarray(h0), xs)
    assert hs.shape == (4, h0.shape[0], h0.shape[1])
    assert np.all(np.isfinite(np.asarray(hT)))


def test_cell_use_pallas_flag():
    cell = LayerNormGRUCell(units=16, layer_norm=True, use_pallas=True)
    key = jax.random.PRNGKey(0)
    h0 = jnp.zeros((4, 16))
    x = jax.random.normal(key, (4, 8))
    params = cell.init(key, h0, x)
    h1, _ = cell.apply(params, h0, x)
    assert h1.shape == (4, 16)
    assert np.all(np.isfinite(np.asarray(h1)))


def test_fused_gru_leading_batch_dims():
    x, h0, w, scale, bias, ref = _flax_reference()
    xt = jnp.stack([jnp.asarray(x)] * 2)
    ht = jnp.stack([jnp.asarray(h0)] * 2)
    out = fused_layernorm_gru(xt, ht, w, scale, bias, interpret=True)
    assert out.shape == (2, h0.shape[0], h0.shape[1])
    np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=2e-5, atol=2e-5)


def test_fused_gru_gradients_match_reference():
    """pallas_call has no reverse-mode rule; the op's custom_vjp must give
    the same gradients as the pure-math path (training differentiates
    through the RSSM scan, so a forward-only op would crash training)."""
    x, h0, w, scale, bias, _ = _flax_reference()

    def loss_fused(x, h, w, s, b):
        return jnp.sum(fused_layernorm_gru(x, h, w, s, b, interpret=True) ** 2)

    from sheeprl_tpu.ops.gru_pallas import _reference_math

    def loss_ref(x, h, w, s, b):
        return jnp.sum(_reference_math(x, h, w, s, b) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, h0, w, scale, bias)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, h0, w, scale, bias)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)
