"""CI smoke for the policy server CLI (run_ci.sh stage 6).

Trains a tiny committed dryrun checkpoint, launches the REAL
``python -m sheeprl_tpu.serve`` process on an ephemeral port, streams a
burst of concurrent HTTP requests through the continuous batcher, checks
the stats surface, and asserts a clean SIGINT shutdown (exit code 0).
"""

import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    from sheeprl_tpu.cli import run
    from sheeprl_tpu.serve.client import PolicyClient
    from tests.ckpt_utils import find_checkpoints

    log_dir = tempfile.mkdtemp(prefix="serve_smoke_")
    run(
        [
            "exp=ppo", "env=dummy", "env.id=discrete_dummy", "dry_run=True",
            "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
            "fabric.devices=1", "fabric.accelerator=cpu", "metric.log_level=0",
            "checkpoint.every=1", "buffer.memmap=False",
            f"log_dir={log_dir}", "print_config=False", "algo.run_test=False",
        ]
    )
    ckpt = find_checkpoints(log_dir)[-1]
    print(f"[serve_smoke] committed checkpoint: {ckpt}")

    proc = subprocess.Popen(
        [
            sys.executable, "-m", "sheeprl_tpu.serve",
            f"checkpoint_path={ckpt}", "serve.port=0", "serve.max_wait_ms=2",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        url = None
        deadline = time.monotonic() + 300
        for line in proc.stdout:
            print(f"[server] {line.rstrip()}")
            m = re.search(r"on (http://[\d.]+:\d+)", line)
            if m:
                url = m.group(1)
                break
            if time.monotonic() > deadline:
                raise TimeoutError("server never announced its address")
        assert url, f"server exited early (rc={proc.poll()})"

        client = PolicyClient(url, timeout=120.0)
        for _ in range(60):  # the socket accepts once the ladder is warm
            try:
                health = client.health()
                break
            except Exception:
                time.sleep(1.0)
        else:
            raise TimeoutError("server never became healthy")
        assert health["ok"] and health["algo"] == "ppo", health

        obs = {
            k: np.zeros(shape, np.dtype(dt))
            for k, (shape, dt) in health["obs_spec"].items()
        }
        action_shape = tuple(health["action_shape"])
        errors = []

        def worker():
            try:
                a = client.act(obs, greedy=True)
                assert a.shape == action_shape, a
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        assert not errors, errors

        stats = client.stats()
        print(f"[serve_smoke] stats: {stats}")
        assert stats["served"] >= 24 and stats["errors"] == 0, stats
        assert np.isfinite(stats["p50_ms"]) and np.isfinite(stats["p99_ms"]), stats

        # the telemetry-hub export on the serve surface (PR 13): the same
        # stats in Prometheus text exposition format at /metrics
        import urllib.request

        with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
            assert resp.status == 200
            ctype = resp.headers.get("Content-Type", "")
            body = resp.read().decode()
        assert ctype == "text/plain; version=0.0.4; charset=utf-8", ctype
        assert "sheeprl_serve_served" in body, body[:400]
        print("[serve_smoke] /metrics OK (Prometheus exposition via the telemetry hub)")

        proc.send_signal(signal.SIGINT)
        rc = proc.wait(60)
        assert rc == 0, f"server exited rc={rc} on SIGINT (expected clean shutdown)"
        print("[serve_smoke] OK: served batched HTTP traffic, clean shutdown")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)


if __name__ == "__main__":
    main()
