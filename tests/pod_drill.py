#!/usr/bin/env python
"""run_ci stage 17: pod-scale fault-tolerance drill (multi-controller).

A short decoupled-PPO run is driven as a REAL 2-process pod — the fake-DCN
protocol spawns a learner cell (rank 0) and an actor cell (rank 1), with
segments/params crossing a process boundary over the learner front — and
the :class:`~sheeprl_tpu.supervisor.PodSupervisor` supervises the whole
pod:

1. once the first snapshot COMMITs, the drill SIGKILLs the ACTOR cell —
   the "host" dies mid-window, exactly a preempted TPU worker;
2. the pod's collective failure semantics fire: no rank trains past a
   dead peer.  The supervisor's sidecar sees the dead cell and runs the
   coordinated teardown (the learner's preemption latch gets a chance at
   a final save; with rank 1 gone the snapshot cannot gather all shards,
   so it stays uncommitted — by design, a committed snapshot always
   represents the WHOLE pod);
3. the episode is classified restartable (``preempted`` via the learner's
   latch postmortem, or ``transient`` if the learner instead died on
   ``PeerLost``), and the supervisor relaunches BOTH ranks with
   ``checkpoint.resume_from=auto`` — a collective restart from the newest
   COMMIT under the shared root;
4. asserted: supervisor exit 0; the audit's crash episode carries the
   per-cell return codes (rank 1 killed by SIGKILL) and a restart action;
   the success episode completes; the newest COMMITTED snapshot sits at
   the FULL configured step count and verifies clean for both ranks.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG_DIR = "/tmp/run_ci_pod"
TOTAL_STEPS = 128  # 16 learner updates x 8 policy steps each
WORLD = 2

RUN_ARGS = [
    "exp=ppo_decoupled",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.max_episode_steps=16",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "topology=pod",
    "topology.env_workers=2",
    "fabric.devices=auto",
    "fabric.accelerator=cpu",
    "fabric.distributed.heartbeat_grace_s=20",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=8",
    # 4 epochs paces the learner: enough steady-state runway that the
    # SIGKILL lands mid-run, well before the final update
    "algo.update_epochs=4",
    f"algo.total_steps={TOTAL_STEPS}",
    "algo.mlp_keys.encoder=[state]",
    "algo.run_test=False",
    "checkpoint.every=16",
    "checkpoint.save_last=False",
    "checkpoint.commit_timeout_s=10",
    "buffer.memmap=False",
    "metric.log_level=1",
    "metric.log_every=1",
    f"log_dir={LOG_DIR}",
    "print_config=False",
    # drill pacing: tight backoff, learner heartbeat on a short leash
    "supervisor.max_restarts=3",
    "supervisor.backoff_base_s=0.2",
    "supervisor.poll_interval_s=1.0",
]


def main() -> int:
    shutil.rmtree(LOG_DIR, ignore_errors=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.supervisor import PodSupervisor

    cfg = compose(RUN_ARGS)
    sup = PodSupervisor(cfg, RUN_ARGS, WORLD)

    # -- the chaos: SIGKILL the actor "host" right after the first COMMIT ----
    killed: list = []

    def killer() -> None:
        while not killed:
            commits = glob.glob(os.path.join(LOG_DIR, "**", "COMMIT"), recursive=True)
            if commits:
                cells = list(sup._cells)
                if len(cells) == WORLD and cells[1].poll() is None:
                    cells[1].send_signal(signal.SIGKILL)
                    killed.append(sorted(commits))
                    print(f"[pod-drill] SIGKILLed actor cell after {commits[0]}", flush=True)
                    return
            time.sleep(0.05)

    threading.Thread(target=killer, name="pod-drill-killer", daemon=True).start()

    rc = sup.run()
    assert rc == 0, f"pod supervisor exited {rc} — the pod never completed"
    assert killed, "the drill never got to SIGKILL the actor cell"

    # -- audit trail: crash episode with per-cell rcs, then success ----------
    audit = sup.audit_path
    assert os.path.isfile(audit), f"no supervisor_log.jsonl at {audit}"
    episodes = [json.loads(line) for line in open(audit)]
    assert len(episodes) == 2, f"expected crash+success episodes, got {episodes}"
    crash, success = episodes
    assert crash["classification"] in ("preempted", "transient"), crash
    assert crash["action"] == "restart", crash
    assert crash["num_processes"] == WORLD, crash
    cell_rcs = {c["rank"]: c["returncode"] for c in crash["cells"]}
    assert cell_rcs[1] == -signal.SIGKILL, f"actor cell rc should be -9: {crash['cells']}"
    assert all(c["returncode"] is not None for c in crash["cells"]), (
        "coordinated teardown left a cell running: " + str(crash["cells"])
    )
    assert success["classification"] == "success" and success["returncode"] == 0, success
    print(f"[pod-drill] audit OK: {audit} ({len(episodes)} episodes, cells={crash['cells']})")

    # -- collective restart resumed from a shared commit and finished --------
    from sheeprl_tpu.checkpoint.protocol import checkpoint_step, step_dir_name, verify_checkpoint

    ckpt_dirs = glob.glob(os.path.join(sup.exp_root, "*", "version_*", "checkpoint"))
    steps = sorted(
        checkpoint_step(p)
        for d in ckpt_dirs
        for p in glob.glob(os.path.join(d, "step_*"))
        if checkpoint_step(p) >= 0 and os.path.exists(os.path.join(p, "COMMIT"))
    )
    assert steps, "no committed snapshots under the experiment root"
    assert steps[-1] == TOTAL_STEPS, (
        f"newest committed snapshot is step {steps[-1]}, expected {TOTAL_STEPS} (all: {steps})"
    )
    # the kill landed after the first commit; the resumed episode continued
    # that history rather than starting over
    assert len(steps) > 1, steps

    newest = next(
        os.path.join(d, step_dir_name(TOTAL_STEPS))
        for d in ckpt_dirs
        if os.path.exists(os.path.join(d, step_dir_name(TOTAL_STEPS)))
    )
    problems = verify_checkpoint(newest)
    assert not problems, f"final pod snapshot fails verification: {problems}"
    print(f"[pod-drill] checkpoints OK: committed steps {steps}; {newest} verifies clean")
    print(
        "pod drill OK: actor host SIGKILLed mid-window -> coordinated teardown "
        "-> collective restart from shared commit -> full step count"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
