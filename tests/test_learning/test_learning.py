"""Opt-in learning-validation tests (minutes each on CPU — `pytest -m slow`).

Prove the algorithms LEARN: reward rises past an absolute threshold and
(DreamerV3) the world-model loss falls.  The fast suite only proves plumbing;
these are the RL-correctness teeth.  Curves from the same workloads are
published by benchmarks/learning_curves.py into docs/curves/.
"""

import os

import pytest

from tests.test_learning.learning_runs import WORKLOADS, check_workload, run_workload

# truly opt-in: an hour-scale suite must not ride along with `pytest tests/`
# (the committed evidence lives in docs/curves/, refreshed by
# benchmarks/learning_curves.py from these SAME workloads)
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.environ.get("SHEEPRL_RUN_LEARNING"),
        reason="opt-in: set SHEEPRL_RUN_LEARNING=1 (curves: benchmarks/learning_curves.py)",
    ),
]


@pytest.mark.parametrize("name", sorted(WORKLOADS))
def test_learning(tmp_path, name):
    rewards, losses = run_workload(name, str(tmp_path / "logs"))
    assert rewards, f"{name}: no episodes completed"
    check_workload(name, rewards, losses)
