"""Opt-in learning-validation tests (minutes each on CPU — `pytest -m slow`).

Prove the algorithms LEARN: reward rises past an absolute threshold and
(DreamerV3) the world-model loss falls.  The fast suite only proves plumbing;
these are the RL-correctness teeth.  Curves from the same workloads are
published by benchmarks/learning_curves.py into docs/curves/.
"""

import pytest

from tests.test_learning.learning_runs import WORKLOADS, check_workload, run_workload

pytestmark = pytest.mark.slow


@pytest.mark.parametrize("name", sorted(WORKLOADS))
def test_learning(tmp_path, name):
    rewards, losses = run_workload(name, str(tmp_path / "logs"))
    assert rewards, f"{name}: no episodes completed"
    check_workload(name, rewards, losses)
