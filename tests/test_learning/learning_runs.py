"""Shared learning-validation workloads.

Used by both the opt-in slow tests (tests/test_learning/test_learning.py)
and the curve-publishing script (benchmarks/learning_curves.py), so the
validated workload and the published artifact are the same program.

Role model: the reference's README agent-performance section
(/root/reference/README.md:23-81) — learning curves are the proof artifact
that the algorithms LEARN, not just run.
"""

from __future__ import annotations

import csv
import time
from pathlib import Path
from typing import Dict, List, Tuple

COMMON = [
    "env.capture_video=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "metric.log_level=1",
    "metric/logger=csv",
    "metric.log_every=500",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "algo.run_test=False",
    "print_config=False",
]

# Each workload: (cli args, reward threshold the LAST QUARTER mean must beat,
# metric whose trend must be DOWN over the run or None).
WORKLOADS: Dict[str, dict] = {
    # reference baseline context: PPO CartPole is the published wall-clock
    # benchmark env; 500 is the env's max return, 400 ≈ solved.
    "ppo_cartpole": {
        "args": [
            "exp=ppo",
            "env.id=CartPole-v1",
            "env.num_envs=4",
            "env.sync_env=True",
            "seed=5",
            "algo.total_steps=60000",
            "algo.rollout_steps=128",
            "algo.per_rank_batch_size=64",
            "algo.update_epochs=4",
            "algo.mlp_keys.encoder=[state]",
        ],
        "reward_threshold": 400.0,
        "random_baseline": (25.6, 15.2),
        "falling_metric": None,
    },
    # Pendulum starts ~-1200/episode; SAC reaches better than -300 when the
    # critic/actor/alpha machinery works.
    "sac_pendulum": {
        "args": [
            "exp=sac",
            "env.id=Pendulum-v1",
            "env.num_envs=4",
            "env.sync_env=True",
            "seed=5",
            "algo.total_steps=20000",
            "algo.learning_starts=1000",
            "algo.per_rank_batch_size=128",
            "algo.replay_ratio=0.5",
            "algo.mlp_keys.encoder=[state]",
            "buffer.size=20000",
        ],
        "reward_threshold": -300.0,
        "random_baseline": (-1225.3, 268.2),
        "falling_metric": None,
    },
    # PIXEL learning teeth (VERDICT r3 weak #3): the agent's position exists
    # ONLY in the image (state key is zeros), so beating random proves the
    # CNN trunk carries the policy signal.  PixelGridDummyEnv: 4×4 grid,
    # 16-step episodes, reward = -manhattan/6 per step.  Measured random
    # baseline (100 episodes): -7.44 ± 3.17, so the mean over a ~25-episode
    # gate window has σ ≈ 0.63 — the -3.0 gate is ~7σ above random while a
    # pixel-sighted PPO reaches -0.8 (VERDICT r4 weak #4: gates re-derived
    # from measured baselines).
    "ppo_pixel_grid": {
        "args": [
            "exp=ppo",
            "env=dummy",
            "env.id=pixel_grid_dummy",
            "env.num_envs=4",
            "env.sync_env=True",
            "seed=5",
            "algo.total_steps=24000",
            "algo.rollout_steps=64",
            "algo.per_rank_batch_size=64",
            "algo.update_epochs=2",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ],
        "reward_threshold": -3.0,
        "random_baseline": (-7.44, 3.17),
        "falling_metric": None,
    },
    # DreamerV3-XS on the same pixel task: CNN encoder/decoder + two-hot
    # reward head must learn (obs loss falls, reward beats random):
    # gate -4.5 ≈ +4.7σ above the random gate-window mean (-7.44, σ≈0.63).
    "dreamer_v3_pixel_grid": {
        "args": [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=pixel_grid_dummy",
            "env.num_envs=1",
            "env.sync_env=True",
            "seed=5",
            "algo=dreamer_v3_XS",
            "algo.total_steps=5000",
            "algo.learning_starts=256",
            "algo.replay_ratio=0.2",
            "algo.per_rank_batch_size=4",
            "algo.per_rank_sequence_length=16",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "buffer.size=5000",
        ],
        "reward_threshold": -4.5,
        "random_baseline": (-7.44, 3.17),
        "falling_metric": "Loss/observation_loss",
    },
    # REAL-PHYSICS teeth (VERDICT r4 missing #2): SAC on dm_control
    # walker-walk from proprioceptive states — the BASELINE.md tracked
    # config #2 task, recipe shaped on the reference's SAC hyperparameters
    # (sheeprl/configs/algo/sac.yaml: batch 256, lr 3e-4, tau 0.005) with
    # the dmc env block of configs/exp/dreamer_v3_dmc_walker_walk.yaml
    # (action_repeat 2).  random_baseline below was measured over 10
    # uniform-action episodes (DMCWrapper, seed 0..9) and is published in
    # docs/curves/LEARNING.md.  Gate 300 = ~9x random, >60 sigma — a
    # half-broken critic/actor stack cannot pass it.
    "sac_walker_walk": {
        "args": [
            "exp=sac",
            "env=dmc",
            "env.id=walker_walk",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.action_repeat=2",
            "env.wrapper.from_pixels=False",
            "seed=5",
            "algo.total_steps=300000",
            "algo.learning_starts=4000",
            "algo.per_rank_batch_size=256",
            "algo.replay_ratio=0.5",
            "algo.mlp_keys.encoder=[state]",
            "buffer.size=300000",
        ],
        "reward_threshold": 300.0,
        "random_baseline": (32.9, 4.0),  # mean, std of 10 random-policy episodes
        "falling_metric": None,
    },
    # DreamerV2 at XS-equivalent sizing on the same pixel task: the V2
    # semantics (ELU, no unimix, alpha-balanced KL, Gaussian reward head,
    # hard target copy, REINFORCE-mixed actor) must LEARN, not just pass
    # goldens — same gate geometry as the DV3 pixel workload.
    "dreamer_v2_pixel_grid": {
        "args": [
            "exp=dreamer_v2",
            "env=dummy",
            "env.id=pixel_grid_dummy",
            "env.num_envs=1",
            "env.sync_env=True",
            "seed=5",
            "algo.dense_units=256",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=24",
            "algo.world_model.recurrent_model.recurrent_state_size=256",
            "algo.world_model.transition_model.hidden_size=256",
            "algo.world_model.representation_model.hidden_size=256",
            "algo.world_model.discrete_size=16",
            "algo.world_model.stochastic_size=16",
            "algo.total_steps=5000",
            "algo.learning_starts=256",
            "algo.replay_ratio=0.2",
            "algo.per_rank_batch_size=4",
            "algo.per_rank_sequence_length=16",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "buffer.size=5000",
        ],
        "reward_threshold": -4.5,
        "random_baseline": (-7.44, 3.17),
        "falling_metric": "Loss/observation_loss",
    },
    # DreamerV3-XS, vector obs only (no CNN => CPU-feasible): world-model
    # loss must fall AND reward must rise well above the random policy.
    "dreamer_v3_cartpole": {
        "args": [
            "exp=dreamer_v3",
            "env.id=CartPole-v1",
            "env.num_envs=1",
            "env.sync_env=True",
            "seed=5",
            "algo=dreamer_v3_XS",
            "algo.total_steps=12000",
            "algo.learning_starts=512",
            "algo.replay_ratio=0.25",
            "algo.per_rank_batch_size=8",
            "algo.per_rank_sequence_length=32",
            "algo.cnn_keys.encoder=[]",
            "algo.mlp_keys.encoder=[state]",
            "buffer.size=12000",
        ],
        # 400 ≈ solved on the 500-max task (the r4 gate of 120 would not
        # have caught a half-broken agent; measured run reaches 489.5)
        "reward_threshold": 400.0,
        "random_baseline": (25.6, 15.2),
        "falling_metric": "Loss/world_model_loss",
    },
}


def run_workload(name: str, log_dir: str) -> Tuple[List[Tuple[int, float]], Dict[str, List[Tuple[int, float]]]]:
    """Run one workload; return (reward curve, all logged loss curves)."""
    from sheeprl_tpu.cli import run

    spec = WORKLOADS[name]
    run(COMMON + spec["args"] + [f"log_dir={log_dir}"])
    return read_curves(log_dir)


def read_curves(log_dir: str):
    csvs = sorted(Path(log_dir).glob("**/metrics.csv"))
    assert csvs, f"no metrics.csv under {log_dir}"
    rewards: List[Tuple[int, float]] = []
    losses: Dict[str, List[Tuple[int, float]]] = {}
    with open(csvs[-1]) as f:
        for row in csv.DictReader(f):
            step, name, value = int(row["step"]), row["name"], float(row["value"])
            if name == "Rewards/rew_avg":
                rewards.append((step, value))
            elif name.startswith("Loss/") or name.startswith("State/"):
                losses.setdefault(name, []).append((step, value))
    return rewards, losses


def last_quarter_mean(curve: List[Tuple[int, float]]) -> float:
    assert curve, "empty curve"
    tail = curve[-max(1, len(curve) // 4):]
    return sum(v for _, v in tail) / len(tail)


def first_last_quarter_means(curve: List[Tuple[int, float]]) -> Tuple[float, float]:
    q = max(1, len(curve) // 4)
    head, tail = curve[:q], curve[-q:]
    return (sum(v for _, v in head) / len(head), sum(v for _, v in tail) / len(tail))


def check_workload(name: str, rewards, losses) -> Dict[str, float]:
    """Assert the workload learned; return a summary dict for publishing."""
    spec = WORKLOADS[name]
    final = last_quarter_mean(rewards)
    assert final >= spec["reward_threshold"], (
        f"{name}: last-quarter mean reward {final:.1f} < threshold {spec['reward_threshold']} "
        f"(curve tail: {rewards[-5:]})"
    )
    summary = {"final_reward": final, "threshold": spec["reward_threshold"]}
    if spec["falling_metric"]:
        head, tail = first_last_quarter_means(losses[spec["falling_metric"]])
        assert tail < head, (
            f"{name}: {spec['falling_metric']} did not fall ({head:.4f} -> {tail:.4f})"
        )
        summary["falling_metric_head"] = head
        summary["falling_metric_tail"] = tail
    return summary
