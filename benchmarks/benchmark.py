"""Wall-clock benchmark harness (reference: benchmarks/benchmark.py:1-52).

Runs any experiment config end-to-end and prints the wall-clock, e.g.:

    python benchmarks/benchmark.py exp=ppo env.id=CartPole-v1 \
        algo.total_steps=65536 metric.log_level=0 checkpoint.every=0 \
        env.capture_video=False algo.run_test=False

The driver-facing single-line JSON benchmark lives in ../bench.py.
"""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    start = time.perf_counter()
    run(sys.argv[1:])
    print(f"wall_clock_s: {time.perf_counter() - start:.2f}")
