"""DV1 benchmark-row decomposition: WHERE does the update budget go?

VERDICT r4 weak #2: the DV1 wall-clock row's "residual ~2× XLA-CPU conv
gap" was asserted from one cProfile run.  This script measures it per-op:

1. builds DreamerV1 at the EXACT benchmark sizing (`dreamer_v1_benchmarks`:
   tiny model, B=50 × L=50 pixel sequences, the reference recipe);
2. times the full jitted world-model update and its components (conv
   encoder fwd+bwd, DeCNN decoder fwd+bwd, RSSM scan) with XLA
   `cost_analysis()` FLOPs → sustained GFLOP/s per component;
3. answers the layout question directly: the decoder-shaped conv
   microbenched as NHWC vs NCHW `dimension_numbers` at the same shapes.

Usage: JAX_PLATFORMS=cpu python benchmarks/dv1_conv_decomposition.py
Prints a markdown table for BENCH_CPU.md.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _timed(fn, *args, n=5):
    """Median wall-time of n calls, blocking on the result via device_sync
    (block_until_ready resolves at dispatch on the axon tunnel — BENCH_TPU.md)."""
    from sheeprl_tpu.utils.utils import device_sync

    device_sync(fn(*args))  # warm/compile
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        device_sync(fn(*args))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _flops(fn, *args) -> float:
    import jax

    try:
        a = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(a, (list, tuple)):
            a = a[0]
        return float(a.get("flops", 0.0))
    except Exception:
        return 0.0


def main() -> int:
    from sheeprl_tpu.utils.utils import force_cpu_backend

    force_cpu_backend()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v1.agent import GaussianWorldModel, build_agent
    from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_phase
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from gymnasium import spaces

    cfg = compose(
        [
            "exp=dreamer_v1_benchmarks",
            "env=dummy",
            "env.id=discrete_dummy",
            "fabric.accelerator=cpu",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "print_config=False",
        ]
    )
    fabric = build_fabric(cfg)
    B = int(cfg.algo.per_rank_batch_size)
    L = int(cfg.algo.per_rank_sequence_length)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_p = params["world_model"]

    rng = np.random.default_rng(0)
    frames = jnp.asarray(rng.normal(size=(L, B, 64, 64, 3)).astype(np.float32))
    rows = []

    # ---- conv encoder fwd+bwd at benchmark shapes -------------------------
    def enc_loss(p, x):
        return world_model.apply(p, {"rgb": x}, method=GaussianWorldModel.encode).sum()

    enc_g = jax.jit(jax.grad(enc_loss))
    t_enc = _timed(enc_g, wm_p, frames)
    f_enc = _flops(jax.grad(enc_loss), wm_p, frames)
    rows.append(("conv encoder fwd+bwd (L·B=2500 frames)", t_enc, f_enc))

    # ---- DeCNN decoder fwd+bwd --------------------------------------------
    stoch = world_model.stoch_flat
    rec = int(cfg.algo.world_model.recurrent_model.recurrent_state_size)
    latent = jnp.asarray(rng.normal(size=(L, B, stoch + rec)).astype(np.float32))

    def dec_loss(p, z):
        out = world_model.apply(p, z, method=GaussianWorldModel.decode)
        return out["rgb"].sum()

    dec_g = jax.jit(jax.grad(dec_loss, argnums=0))
    t_dec = _timed(dec_g, wm_p, latent)
    f_dec = _flops(jax.grad(dec_loss, argnums=0), wm_p, latent)
    rows.append(("DeCNN decoder fwd+bwd (2500 frames -> 64x64)", t_dec, f_dec))

    # ---- full world-model update (the real train component) ---------------
    wm_opt, actor_opt, critic_opt, opt_state = _dv1_optimizers(fabric, cfg, params)
    train_phase = make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
    )
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (1, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (1, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(1, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((1, L, B), jnp.float32),
        "is_first": jnp.zeros((1, L, B), jnp.float32),
    }

    def one_update(p, o, b):
        return train_phase(p, o, b, jax.random.PRNGKey(0), jnp.int32(0))

    # donation: the train phase donates params/opt-state, so give every
    # timed call its own copies; time with n=3
    def run_update():
        p = jax.tree.map(jnp.copy, params)
        o = jax.tree.map(jnp.copy, opt_state)
        return one_update(p, o, block)

    t_full = _timed(run_update, n=3)
    rows.append(("FULL train update (WM + behavior, one dispatch)", t_full, 0.0))

    # ---- layout A/B: decoder-shaped transposed conv NHWC vs NCHW ----------
    # the heaviest decoder layer: upsample to 64x64 with tiny channels
    x_nhwc = jnp.asarray(rng.normal(size=(2500, 32, 32, 4)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(4, 4, 4, 2)).astype(np.float32))  # HWIO

    pad = [(2, 2), (2, 2)]  # 4x4 kernel, stride-2 transposed conv -> exact 2x upsample

    def conv_nhwc(x, k):
        return jax.lax.conv_general_dilated(
            x, k, window_strides=(1, 1), padding=pad,
            lhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    x_nchw = jnp.transpose(x_nhwc, (0, 3, 1, 2))
    k_oihw = jnp.transpose(k, (3, 2, 0, 1))

    def conv_nchw(x, k):
        return jax.lax.conv_general_dilated(
            x, k, window_strides=(1, 1), padding=pad,
            lhs_dilation=(2, 2),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    t_nhwc = _timed(jax.jit(conv_nhwc), x_nhwc, k)
    t_nchw = _timed(jax.jit(conv_nchw), x_nchw, k_oihw)
    rows.append(("layout A/B: upsampling conv NHWC", t_nhwc, _flops(conv_nhwc, x_nhwc, k)))
    rows.append(("layout A/B: upsampling conv NCHW", t_nchw, _flops(conv_nchw, x_nchw, k_oihw)))

    # ---- report -----------------------------------------------------------
    print("\n| component | time | GFLOP | GFLOP/s |")
    print("|---|---|---|---|")
    for name, t, f in rows:
        gfs = f / t / 1e9 if f else 0.0
        print(
            f"| {name} | {t * 1e3:.1f} ms | "
            f"{f / 1e9:.2f} | {gfs:.1f} |" if f else f"| {name} | {t * 1e3:.1f} ms | — | — |"
        )
    print(
        f"\nlayout verdict: NCHW/NHWC = {t_nchw / t_nhwc:.2f}x "
        f"({'NHWC wins — layout is NOT the gap' if t_nhwc <= t_nchw else 'NCHW faster — layout IS the gap'})"
    )
    return 0


def _dv1_optimizers(fabric, cfg, params):
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    return build_dv3_optimizers(fabric, cfg, params)


if __name__ == "__main__":
    sys.exit(main())
