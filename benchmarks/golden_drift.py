"""Golden drift rehearsal across varied XLA-CPU configurations.

VERDICT r4 weak #6: the goldens' foreign-platform tolerance (RTOL_FOREIGN)
had never been validated against a second platform — the first TPU run
would hit an untested tolerance.  This harness re-runs every golden family
under varied XLA-CPU compilation configs in child processes (XLA_FLAGS must
be set before jax initializes) and records the measured per-family drift
against `goldens.json`, turning the tolerance into data.

Usage:
    JAX_PLATFORMS=cpu python benchmarks/golden_drift.py            # all configs
    JAX_PLATFORMS=cpu python benchmarks/golden_drift.py --child <cfg>  # internal

Writes `tests/test_regression/DRIFT.md`.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

GOLDENS = REPO / "tests" / "test_regression" / "goldens.json"
OUT_MD = REPO / "tests" / "test_regression" / "DRIFT.md"

# Each config is an XLA_FLAGS suffix appended to the inherited flags.
# fast-math OFF is the interesting direction (XLA-CPU defaults it on, so
# every golden was captured under fast-math); the thunk-runtime toggle
# swaps the whole CPU executable layer, a proxy for "different XLA build".
CONFIGS = {
    "no_fast_math": "--xla_cpu_enable_fast_math=false",
    "legacy_runtime": "--xla_cpu_use_thunk_runtime=false",
    "vector_width_128": "--xla_cpu_prefer_vector_width=128",
}


def _child(cfg_name: str) -> None:
    import tempfile

    from sheeprl_tpu.cli import run
    from tests.test_regression.test_golden import COMMON, FAMILIES, _last_metrics

    common = list(COMMON)
    if cfg_name == "tpu_chip":
        # run the SAME golden recipes on the real chip: ambient (axon)
        # backend, fp32 params — the drift measured is platform numerics
        # (MXU matmul path, conv layout), exactly what RTOL_FOREIGN guards
        common = [a for a in common if a != "fabric.accelerator=cpu"]
        common.append("fabric.accelerator=tpu")
    else:
        from sheeprl_tpu.utils.utils import force_cpu_backend

        force_cpu_backend()

    results = {}
    for family, args in sorted(FAMILIES.items()):
        with tempfile.TemporaryDirectory() as tmp:
            run(common + args + [f"log_dir={tmp}/logs"])
            results[family] = _last_metrics(Path(tmp))
        print(f"[golden_drift:{cfg_name}] {family} done", file=sys.stderr, flush=True)
    print("RESULTS " + json.dumps(results), flush=True)


def _drift(got: dict, expected: dict) -> tuple:
    """Max relative deviation over the shared metrics;
    returns (drift, worst_metric_name, n_compared)."""
    shared = set(got) & set(expected)
    worst, worst_name = 0.0, "-"
    for name in shared:
        e, g = expected[name], got[name]
        rel = abs(g - e) / max(abs(e), 1e-5)
        if rel > worst:
            worst, worst_name = rel, name
    return worst, worst_name, len(shared)


def _tpu_mode() -> int:
    """Run the golden families once on the real chip and APPEND a
    second-platform drift table to DRIFT.md (the CPU-config table the
    main mode writes is left untouched)."""
    goldens = json.loads(GOLDENS.read_text())
    families = sorted(k for k in goldens if not k.startswith("__"))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run(
        [sys.executable, __file__, "--child", "tpu_chip"],
        env=env,
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    line = next((l for l in proc.stdout.splitlines() if l.startswith("RESULTS ")), None)
    if proc.returncode != 0 or line is None:
        print(
            f"[golden_drift] tpu_chip FAILED (rc={proc.returncode}):\n{proc.stderr[-3000:]}",
            flush=True,
        )
        return 1
    results = json.loads(line[len("RESULTS "):])
    rows = {fam: _drift(results.get(fam, {}), goldens[fam]) for fam in families}
    worst = max((d for d, _, _ in rows.values()), default=0.0)
    # the gate verdict must apply the SAME rule test_golden does: rel within
    # RTOL_FOREIGN, or abs within the metric's documented ATOL_FOREIGN
    # carve-out (cancellation-prone metrics)
    from tests.test_regression.test_golden import ATOL, ATOL_FOREIGN, RTOL_FOREIGN

    failures = []
    for fam in families:
        for name, want in goldens[fam].items():
            have = results.get(fam, {}).get(name)
            if have is None:
                continue
            if not math.isfinite(have):
                # NaN compares False against every threshold, so a diverged
                # chip run used to sail through the gate — non-finite is an
                # explicit failure, not a pass
                failures.append(f"{fam}:{name} (non-finite: {have})")
                continue
            delta = abs(have - want)
            atol = max(ATOL, ATOL_FOREIGN.get(f"{fam}:{name}", 0.0))
            if delta > RTOL_FOREIGN * abs(want) and delta > atol:
                failures.append(f"{fam}:{name}")
    lines = [
        "",
        "## Second platform: real TPU (v5e, axon)",
        "",
        "Same golden recipes, `fabric.accelerator=tpu`, fp32 params, default",
        "TPU matmul precision.  Max relative deviation vs the CPU-captured",
        "`goldens.json`:",
        "",
        "| family | drift (worst metric) |",
        "|---|---|",
    ]
    for fam in families:
        drift, name, n = rows[fam]
        if n == 0:
            lines.append(f"| {fam} | NO METRICS |")
        else:
            lines.append(f"| {fam} | {drift:.1e} ({name.removeprefix('Loss/')}, {n} metrics) |")
    verdict = (
        "**gate GREEN** (every metric within rtol 5e-2 or its documented "
        "ATOL_FOREIGN carve-out)"
        if not failures
        else f"**gate RED**: {', '.join(failures)} outside both tolerances"
    )
    lines += [
        "",
        f"Worst relative drift: **{worst:.2e}**.  test_golden foreign gate: {verdict}.",
        "",
    ]
    # idempotent append: drop any previous TPU section (re-runs must not
    # stack duplicates), keep the CPU-config table above it
    marker = "\n## Second platform: real TPU"
    existing = OUT_MD.read_text() if OUT_MD.exists() else ""
    if marker in existing:
        existing = existing[: existing.index(marker)]
    OUT_MD.write_text(existing + "\n".join(lines))
    print(f"[golden_drift] appended TPU table to {OUT_MD} (worst {worst:.2e})", flush=True)
    # a RED gate must fail the stage (tpu_revival records rc==0 as ok)
    return 1 if failures else 0


def main() -> int:
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        _child(sys.argv[2])
        return 0
    if "--tpu" in sys.argv:
        return _tpu_mode()

    goldens = json.loads(GOLDENS.read_text())
    families = sorted(k for k in goldens if not k.startswith("__"))
    # preserve a committed TPU section across CPU-mode rewrites
    tpu_marker = "\n## Second platform: real TPU"
    prior = OUT_MD.read_text() if OUT_MD.exists() else ""
    tpu_section = prior[prior.index(tpu_marker):] if tpu_marker in prior else ""
    table: dict = {}
    for cfg_name, flags in CONFIGS.items():
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") + " " + flags).strip(),
        }
        print(f"[golden_drift] running config {cfg_name}: {flags}", flush=True)
        proc = subprocess.run(
            [sys.executable, __file__, "--child", cfg_name],
            env=env,
            capture_output=True,
            text=True,
            cwd=str(REPO),
        )
        line = next(
            (l for l in proc.stdout.splitlines() if l.startswith("RESULTS ")), None
        )
        if proc.returncode != 0 or line is None:
            print(
                f"[golden_drift] {cfg_name} FAILED (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}",
                flush=True,
            )
            table[cfg_name] = None
            continue
        results = json.loads(line[len("RESULTS "):])
        table[cfg_name] = {
            fam: _drift(results.get(fam, {}), goldens[fam]) for fam in families
        }

    # ---- render -----------------------------------------------------------
    import platform as _platform

    import jax

    lines = [
        "# Golden drift across varied XLA-CPU configurations",
        "",
        "Measured by `benchmarks/golden_drift.py`: every golden family re-run",
        "in a child process with the named `XLA_FLAGS` variation, max relative",
        "deviation vs `goldens.json` over the golden metrics.  Context for the",
        "tolerances in `test_golden.py`: same-config rtol "
        "5e-3, foreign-platform rtol 5e-2.",
        "",
        f"Host: {_platform.machine()}/{_platform.system()}, jax {jax.__version__}.",
        "",
        "| family | " + " | ".join(table) + " |",
        "|---|" + "---|" * len(table),
    ]
    for fam in families:
        cells = []
        for cfg_name in table:
            if table[cfg_name] is None:
                cells.append("config failed")
                continue
            drift, name, n = table[cfg_name][fam]
            if n == 0:
                cells.append("NO METRICS")
            elif drift == 0.0:
                cells.append(f"bit-identical ({n} metrics)")
            else:
                cells.append(f"{drift:.1e} ({name.removeprefix('Loss/')})")
        lines.append(f"| {fam} | " + " | ".join(cells) + " |")
    worst_overall = max(
        (d for cfg in table.values() if cfg for d, _, _ in cfg.values()), default=0.0
    )
    lines += [
        "",
        f"Worst drift overall: **{worst_overall:.2e}** "
        f"({'within' if worst_overall < 5e-2 else 'EXCEEDS'} the 5e-2 "
        "foreign-platform tolerance).",
        "",
        "Reading: configs that only swap the executable layer reproduce the",
        "goldens bit-for-bit; changing codegen vector width changes reduction",
        "orders and surfaces real drift, largest on the most chaotic metric",
        "(a Dreamer policy loss after a full update).  The measured",
        "cross-codegen drift is two orders of magnitude inside RTOL_FOREIGN —",
        "evidence the widened tolerance absorbs compiler-level numerics",
        "changes without masking real regressions (same-config RTOL stays",
        "the tight gate).",
        "",
    ]
    OUT_MD.write_text("\n".join(lines) + tpu_section)
    print(f"[golden_drift] wrote {OUT_MD} (worst {worst_overall:.2e})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
