"""TPU-revival self-capture harness (VERDICT r3 #1: prober -> actor).

Three rounds of benches have been blocked on a wedged accelerator tunnel;
twice it revived briefly between probes and the window was lost.  This
script converts tunnel luck into zero-latency capture: the /tmp watchdog
loop invokes it the moment a real dispatch succeeds, and it runs the full
staged capture sequence, appending every result to BENCH_TPU.md and
committing the artifact:

  1. ``python bench.py``            — DV3-S B=16 L=64 updates/s + MFU
                                      (baseline 0.5 updates/s, RTX 3080,
                                      /root/reference/README.md:44-51)
  2. ``benchmarks/bench_gru_pallas.py`` — Pallas vs XLA A/B at preset shapes
  3. XL shape check                 — BENCH_SIZE=XL single update compiles+runs
  4. partial DV3-S learning run     — ~30 min pixel DMC walker_walk slice,
                                      curve appended

Each stage runs in a child process under its own hard timeout so a re-wedge
mid-capture loses one stage, not the harness.  A lock file makes the capture
run at most once per revival; stages already marked done are skipped so a
second revival resumes where the first died.

Usage:  python benchmarks/tpu_revival.py            (invoked by the watchdog)
        FORCE=1 python benchmarks/tpu_revival.py    (ignore the done-marks)
"""

from __future__ import annotations

import datetime
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "BENCH_TPU.md"
STATE = REPO / "benchmarks" / ".tpu_revival_state.json"
LOCK = pathlib.Path("/tmp/tpu_revival.lock")

STAGES = [
    # (name, argv, extra env, timeout seconds).  Honest-timing era (r5s3):
    # every timed stage goes through utils.device_sync — see BENCH_TPU.md's
    # timing-validity note for why block_until_ready cannot be trusted here.
    (
        "dv3_s_bench_honest",
        [sys.executable, "bench.py"],
        {"BENCH_TIMEOUT": "1800", "BENCH_ITERS": "10"},
        2100,
    ),
    (
        "pallas_ab_scan",
        [sys.executable, "benchmarks/bench_gru_pallas.py"],
        {},
        3000,
    ),
    (
        "xl_shape_check_honest",
        [sys.executable, "bench.py"],
        {"BENCH_SIZE": "XL", "BENCH_B": "8", "BENCH_L": "32", "BENCH_U": "1",
         "BENCH_ITERS": "6", "BENCH_TIMEOUT": "2400"},
        2500,
    ),
    (
        "golden_drift_tpu",
        [sys.executable, "benchmarks/golden_drift.py", "--tpu"],
        {},
        3500,
    ),
    (
        "dreamer_v3_wall_on_chip",
        [sys.executable, "bench.py"],
        {"BENCH_TARGET": "dreamer_v3_wall", "BENCH_ON_ACCEL": "1",
         "BENCH_ARGS": "env=dummy env.id=discrete_dummy",  # no ALE in image
         "BENCH_TIMEOUT": "3600"},
        3700,
    ),
    (
        "dreamer_v2_wall_on_chip",
        [sys.executable, "bench.py"],
        {"BENCH_TARGET": "dreamer_v2_wall", "BENCH_ON_ACCEL": "1",
         "BENCH_ARGS": "env=dummy env.id=discrete_dummy",
         "BENCH_TIMEOUT": "3600"},
        3700,
    ),
    (
        "dreamer_v1_wall_on_chip",
        [sys.executable, "bench.py"],
        {"BENCH_TARGET": "dreamer_v1_wall", "BENCH_ON_ACCEL": "1",
         "BENCH_ARGS": "env=dummy env.id=discrete_dummy",
         "BENCH_TIMEOUT": "3600"},
        3700,
    ),
    (
        "dv3_s_dmc_learning",
        [
            sys.executable,
            "-m",
            "sheeprl_tpu",
            "exp=dreamer_v3_dmc_walker_walk",
            "algo=dreamer_v3_S",
            "algo.total_steps=20000",
            "algo.learning_starts=1024",
            "algo.run_test=False",
            "env.num_envs=1",
            "buffer.size=25000",
            "buffer.device=True",
            "buffer.memmap=False",
            "metric.log_level=1",
            "metric/logger=csv",
            "metric.log_every=500",
            "checkpoint.every=0",
            "checkpoint.save_last=True",
            "print_config=False",
            "log_dir=/tmp/tpu_revival_learning",
        ],
        {"MUJOCO_GL": "egl"},
        2900,  # whatever it reached is the datapoint
    ),
]


def load_state() -> dict:
    try:
        return json.loads(STATE.read_text())
    except (OSError, ValueError):
        return {}


def mark(state: dict, name: str, rec: dict) -> None:
    state[name] = rec
    STATE.write_text(json.dumps(state, indent=2) + "\n")


def append_md(title: str, body: str) -> None:
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    if not OUT.exists():
        OUT.write_text(
            "# TPU capture log\n\nAppended automatically by "
            "`benchmarks/tpu_revival.py` on tunnel revival.\n"
        )
    with OUT.open("a") as f:
        f.write(f"\n## {title} ({stamp})\n\n{body}\n")


def tail_learning_curve(log_root: str) -> str:
    """Summarize the partial learning run's metrics.csv (even a killed run
    leaves a readable curve)."""
    import csv

    rows = []
    for p in sorted(pathlib.Path(log_root).glob("**/metrics.csv")):
        with open(p) as f:
            rows += [r for r in csv.DictReader(f)]
    if not rows:
        return "no metrics logged"
    lines = ["| step | metric | value |", "|---|---|---|"]
    keep = ("Rewards/rew_avg", "Loss/world_model_loss", "Loss/policy_loss", "Loss/value_loss")
    kept = [r for r in rows if r.get("name") in keep]
    for r in kept[-24:]:
        lines.append(f"| {r['step']} | {r['name']} | {float(r['value']):.4f} |")
    return "\n".join(lines)


def run_stage(name: str, argv: list, env_extra: dict, timeout_s: int) -> dict:
    env = {**os.environ, **env_extra}
    try:
        child = subprocess.run(
            argv, cwd=REPO, env=env, timeout=timeout_s, capture_output=True, text=True
        )
        out = (child.stdout or "").strip()
        err_tail = "\n".join((child.stderr or "").strip().splitlines()[-10:])
        # a CPU-fallback bench exits 0 but is NOT the TPU capture this
        # harness exists for — don't mark the stage done or the real
        # number is never taken without FORCE=1
        ok = child.returncode == 0 and "CPU fallback" not in out
    except subprocess.TimeoutExpired as e:
        out = ((e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")).strip()
        err_tail = f"TIMEOUT after {timeout_s}s"
        ok = False
    body = f"```\n{out[-4000:] or '(no stdout)'}\n```"
    if not ok:
        body += f"\n\nstage rc!=0 / timeout; stderr tail:\n```\n{err_tail[-1500:]}\n```"
    if name == "dv3_s_dmc_partial_learning":
        body += "\n\ncurve tail:\n\n" + tail_learning_curve("/tmp/tpu_revival_learning")
    append_md(name, body)
    return {"ok": ok, "stdout_tail": out[-400:], "when": datetime.datetime.now().isoformat()}


def git_commit() -> None:
    subprocess.run(["git", "add", "BENCH_TPU.md", str(STATE.relative_to(REPO))], cwd=REPO)
    subprocess.run(
        ["git", "commit", "-m", "TPU capture: bench + Pallas A/B + partial learning run"],
        cwd=REPO,
        capture_output=True,
    )


def main() -> int:
    # at-most-once per revival: O_EXCL lock, held for the process lifetime
    try:
        fd = os.open(LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
    except FileExistsError:
        # stale lock from a dead capture? only steal it if that pid is GONE —
        # PermissionError means the pid exists (another user): NOT stale
        try:
            pid = int(LOCK.read_text())
            os.kill(pid, 0)
            print("[tpu_revival] capture already running; exiting")
            return 0
        except PermissionError:
            print("[tpu_revival] capture already running (other user); exiting")
            return 0
        except (ValueError, ProcessLookupError):
            LOCK.write_text(str(os.getpid()))

    try:
        # the watchdog invokes this on a CONFIRMED live dispatch; a stale
        # 'wedged' probe-cache entry (TTL 600s) must not make bench.py fall
        # back to CPU during the live window
        sys.path.insert(0, str(REPO))
        from sheeprl_tpu.utils.utils import _PROBE_CACHE_PATH

        try:
            os.unlink(_PROBE_CACHE_PATH)
        except OSError:
            pass
        state = {} if os.environ.get("FORCE") else load_state()
        for name, argv, env_extra, timeout_s in STAGES:
            if state.get(name, {}).get("ok"):
                print(f"[tpu_revival] {name}: already captured, skipping")
                continue
            print(f"[tpu_revival] running {name} ...", flush=True)
            rec = run_stage(name, argv, env_extra, timeout_s)
            mark(state, name, rec)
            git_commit()
            print(f"[tpu_revival] {name}: ok={rec['ok']}", flush=True)
        return 0
    finally:
        try:
            LOCK.unlink()
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
