"""Benchmark the Pallas fused LayerNorm-GRU cell vs the plain XLA path on TPU.

VERDICT.md round-1 item 8: the kernel was interpret-validated only; decide on
real hardware whether it wins (enable by default) or loses (remove the dead
fast-path).  Shapes cover the Dreamer presets' recurrent sizes
(S=512, M=1024, L=2048, XL=4096 — reference
sheeprl/algos/dreamer_v3/agent.py world-model sizes) at rollout (B=4/16) and
training (B=16*64 flattened scan step is B per step, so B=16) batch shapes.

Usage:  python benchmarks/bench_gru_pallas.py
Prints one JSON line per (H, B) with xla_us, pallas_us, speedup.
"""

from __future__ import annotations

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.gru_pallas import fused_layernorm_gru

# XLA baselines ARE the ops' reference math — one implementation, no drift
from sheeprl_tpu.ops.gru_pallas import _reference_math as _gru_reference
from sheeprl_tpu.ops.rssm_pallas import _reference_math as _rssm_reference

xla_layernorm_gru = jax.jit(_gru_reference)


def timeit(step, h0, iters=None, scan_len=None):
    """Per-step microseconds of ``h = step(h)`` iterated inside ``lax.scan``.

    Two layers of defense against tunnel measurement artifacts
    (BENCH_TPU.md timing-validity note):

    - the step runs under ``lax.scan`` in ONE jitted program per dispatch
      (``scan_len`` steps each) — eager per-call timing measures the host's
      ~200 µs dispatch rate, not a µs-scale kernel, and the scan is also
      exactly how the RSSM consumes these kernels in training;
    - completion is bounded by ``device_sync`` (D2H scalar materialization),
      never ``block_until_ready`` (dispatch-time no-op on the tunnel).

    Outer dispatches are chained (data-dependent) and auto-scaled so the
    run dominates the ~65 ms sync floor."""
    from functools import partial

    from jax import lax

    from sheeprl_tpu.utils.utils import device_sync

    on_tpu = jax.default_backend() == "tpu"
    if scan_len is None:
        # interpret-mode pallas on CPU is a correctness path, not a perf
        # path — keep smoke runs short; real numbers need the TPU
        scan_len = 256 if on_tpu else 2
    scanned = jax.jit(
        partial(
            lambda n, h: lax.scan(lambda c, _: (step(c), None), h, None, length=n)[0],
            scan_len,
        )
    )
    h = scanned(h0)
    device_sync(h)
    calibrating = iters is None
    if calibrating:
        iters = 4 if on_tpu else 1
    t0 = time.perf_counter()
    h = h0
    for _ in range(iters):
        h = scanned(h)
    device_sync(h)
    dt = time.perf_counter() - t0
    if calibrating and on_tpu:
        # rescale until the chain dominates the sync floor
        attempts = 0
        while dt < 0.5 and iters < 100_000 and attempts < 6:
            iters = max(iters + 1, int(iters * 0.6 / max(dt, 1e-6)))
            t0 = time.perf_counter()
            h = h0
            for _ in range(iters):
                h = scanned(h)
            device_sync(h)
            dt = time.perf_counter() - t0
            attempts += 1
    return dt / (iters * scan_len) * 1e6  # us per step


def main():
    rng = np.random.default_rng(0)
    results = []
    for H in (512, 1024, 2048, 4096):
        D = H  # Dreamer uses dense-projected input of the same width
        for B in (4, 16, 64, 256):
            x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
            h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
            w = jnp.asarray(rng.normal(size=(D + H, 3 * H)).astype(np.float32) * 0.02)
            scale = jnp.ones((3 * H,), jnp.float32)
            bias = jnp.zeros((3 * H,), jnp.float32)

            ref = xla_layernorm_gru(x, h, w, scale, bias)
            try:
                got = fused_layernorm_gru(x, h, w, scale, bias)
            except ValueError as e:  # VMEM budget guard: S-class only
                print(json.dumps({"H": H, "B": B, "skipped": str(e)[:80]}), flush=True)
                continue
            err = float(jnp.max(jnp.abs(ref - got)))

            xla_us = timeit(lambda hh: xla_layernorm_gru(x, hh, w, scale, bias), h)
            pal_us = timeit(lambda hh: fused_layernorm_gru(x, hh, w, scale, bias), h)
            rec = {
                "H": H,
                "B": B,
                "xla_us": round(xla_us, 1),
                "pallas_us": round(pal_us, 1),
                "speedup": round(xla_us / pal_us, 3),
                "max_abs_err": err,
                "platform": jax.devices()[0].platform,
            }
            results.append(rec)
            print(json.dumps(rec), flush=True)
    wins = sum(1 for r in results if r["speedup"] > 1.05)
    print(json.dumps({"summary": f"gru: pallas wins {wins}/{len(results)} shapes"}))
    bench_fused_rssm()


def bench_fused_rssm():
    """Whole-recurrent-path kernel (ops/rssm_pallas.py) vs the two-matmul XLA
    path, at Dreamer preset shapes (D = dense_units, H = recurrent size)."""
    from sheeprl_tpu.ops.rssm_pallas import fused_rssm_recurrent

    xla_path = jax.jit(_rssm_reference)

    rng = np.random.default_rng(1)
    results = []
    # (D=dense_units, H=recurrent): S=(512,512), M=(640,1024), L=(768,2048)
    for D, H in ((512, 512), (640, 1024), (768, 2048)):
        ZA = H + 6  # stoch_flat + actions, ~H for the presets
        for B in (16, 64, 256):
            x = jnp.asarray(rng.normal(size=(B, ZA)).astype(np.float32))
            h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
            w_in = jnp.asarray(rng.normal(size=(ZA, D)).astype(np.float32) * 0.02)
            b_in = jnp.zeros((D,), jnp.float32)
            ls = jnp.ones((D,), jnp.float32)
            lb = jnp.zeros((D,), jnp.float32)
            w_gru = jnp.asarray(rng.normal(size=(D + H, 3 * H)).astype(np.float32) * 0.02)
            gs = jnp.ones((3 * H,), jnp.float32)
            gb = jnp.zeros((3 * H,), jnp.float32)
            args = (x, h, w_in, b_in, ls, lb, w_gru, gs, gb)
            ref = xla_path(*args)
            try:
                got = fused_rssm_recurrent(x, h, w_in, b_in, ls, lb, w_gru, gs, gb)
            except ValueError as e:  # VMEM budget guard: S-class only
                print(json.dumps({"D": D, "H": H, "B": B, "skipped": str(e)[:80]}), flush=True)
                continue
            err = float(jnp.max(jnp.abs(ref - got)))
            xla_us = timeit(lambda hh: xla_path(x, hh, w_in, b_in, ls, lb, w_gru, gs, gb), h)
            pal_us = timeit(
                lambda hh: fused_rssm_recurrent(x, hh, w_in, b_in, ls, lb, w_gru, gs, gb), h
            )
            rec = {
                "kernel": "fused_rssm",
                "D": D,
                "H": H,
                "B": B,
                "xla_us": round(xla_us, 1),
                "pallas_us": round(pal_us, 1),
                "speedup": round(xla_us / pal_us, 3),
                "max_abs_err": err,
                "platform": jax.devices()[0].platform,
            }
            results.append(rec)
            print(json.dumps(rec), flush=True)
    wins = sum(1 for r in results if r["speedup"] > 1.05)
    print(json.dumps({"summary": f"fused_rssm: pallas wins {wins}/{len(results)} shapes"}))


if __name__ == "__main__":
    main()
