"""Benchmark the Pallas fused LayerNorm-GRU cell vs the plain XLA path on TPU.

VERDICT.md round-1 item 8: the kernel was interpret-validated only; decide on
real hardware whether it wins (enable by default) or loses (remove the dead
fast-path).  Shapes cover the Dreamer presets' recurrent sizes
(S=512, M=1024, L=2048, XL=4096 — reference
sheeprl/algos/dreamer_v3/agent.py world-model sizes) at rollout (B=4/16) and
training (B=16*64 flattened scan step is B per step, so B=16) batch shapes.

Usage:  python benchmarks/bench_gru_pallas.py
Prints one JSON line per (H, B) with xla_us, pallas_us, speedup.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.gru_pallas import fused_layernorm_gru

LN_EPS = 1e-5


@jax.jit
def xla_layernorm_gru(x, h, w, scale, bias):
    """Reference XLA path: same math as models.LayerNormGRUCell."""
    inp = jnp.concatenate([x.astype(jnp.float32), h.astype(jnp.float32)], -1)
    parts = jnp.dot(inp, w.astype(jnp.float32), preferred_element_type=jnp.float32)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    var = jnp.mean((parts - mean) ** 2, axis=-1, keepdims=True)
    parts = (parts - mean) * jax.lax.rsqrt(var + LN_EPS)
    parts = parts * scale.reshape(1, -1) + bias.reshape(1, -1)
    H = h.shape[-1]
    reset = jax.nn.sigmoid(parts[:, :H])
    cand = jnp.tanh(reset * parts[:, H : 2 * H])
    update = jax.nn.sigmoid(parts[:, 2 * H :] - 1.0)
    return update * cand + (1.0 - update) * h.astype(jnp.float32)


def timeit(fn, *args, iters=200):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e6  # us


def main():
    rng = np.random.default_rng(0)
    results = []
    for H in (512, 1024, 2048, 4096):
        D = H  # Dreamer uses dense-projected input of the same width
        for B in (4, 16, 64, 256):
            x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
            h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
            w = jnp.asarray(rng.normal(size=(D + H, 3 * H)).astype(np.float32) * 0.02)
            scale = jnp.ones((3 * H,), jnp.float32)
            bias = jnp.zeros((3 * H,), jnp.float32)

            ref = xla_layernorm_gru(x, h, w, scale, bias)
            got = fused_layernorm_gru(x, h, w, scale, bias)
            err = float(jnp.max(jnp.abs(ref - got)))

            xla_us = timeit(xla_layernorm_gru, x, h, w, scale, bias)
            pal_us = timeit(fused_layernorm_gru, x, h, w, scale, bias)
            rec = {
                "H": H,
                "B": B,
                "xla_us": round(xla_us, 1),
                "pallas_us": round(pal_us, 1),
                "speedup": round(xla_us / pal_us, 3),
                "max_abs_err": err,
                "platform": jax.devices()[0].platform,
            }
            results.append(rec)
            print(json.dumps(rec), flush=True)
    wins = sum(1 for r in results if r["speedup"] > 1.05)
    print(json.dumps({"summary": f"pallas wins {wins}/{len(results)} shapes"}))


if __name__ == "__main__":
    main()
