"""Run the learning-validation workloads and publish curves to docs/curves/.

Usage:  JAX_PLATFORMS=cpu python benchmarks/learning_curves.py [workload ...]

Writes, per workload:
  docs/curves/<name>.json   — {"rewards": [[step, value], ...], "losses": {...}}
  docs/curves/<name>.png    — reward curve (when matplotlib is available)
and refreshes docs/curves/LEARNING.md with the summary table.

This is this framework's equivalent of the reference README's agent-
performance section (/root/reference/README.md:23-81): committed evidence
that the implementations learn, reproducible with one command.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tests.test_learning.learning_runs import (  # noqa: E402
    WORKLOADS,
    check_workload,
    last_quarter_mean,
    run_workload,
)

CURVES_DIR = REPO / "docs" / "curves"


def _plot(name: str, rewards) -> bool:
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        return False
    steps, vals = zip(*rewards)
    fig, ax = plt.subplots(figsize=(6, 3.5))
    ax.plot(steps, vals, lw=1.5)
    ax.axhline(WORKLOADS[name]["reward_threshold"], ls="--", lw=1, color="gray")
    ax.set_xlabel("env steps")
    ax.set_ylabel("Rewards/rew_avg")
    ax.set_title(name)
    fig.tight_layout()
    fig.savefig(CURVES_DIR / f"{name}.png", dpi=120)
    plt.close(fig)
    return True


def _write_index(results: dict) -> None:
    lines = [
        "# Learning validation curves",
        "",
        "CPU runs of `benchmarks/learning_curves.py` (same workloads as the",
        "opt-in slow tests in `tests/test_learning/`).  `final` is the mean of",
        "the last quarter of logged `Rewards/rew_avg` points.",
        "",
        "| workload | final reward | threshold | random baseline | wall-clock | status |",
        "|---|---|---|---|---|---|",
    ]
    for name, r in sorted(results.items()):
        status = "PASS" if r["final_reward"] >= r["threshold"] else "FAIL"
        base = WORKLOADS.get(name, {}).get("random_baseline")
        base_s = f"{base[0]:.1f} ± {base[1]:.1f}" if base else "—"
        lines.append(
            f"| {name} | {r['final_reward']:.1f} | {r['threshold']} | {base_s} "
            f"| {r['wall_clock_s']:.0f}s | {status} |"
        )
    lines.extend(
        [
            "",
            "Random baselines are the mean ± std episode return of a",
            "uniform-random policy (10-100 episodes) on the same wrapper stack",
            "(measured once, recorded in `learning_runs.py`); thresholds are",
            "chosen many standard deviations above them so a half-broken agent",
            "cannot pass.",
            "",
        ]
    )
    partials_path = CURVES_DIR / "partials.json"
    if partials_path.exists():
        partials = json.loads(partials_path.read_text())
        lines.extend(
            [
                "## Partial / exploratory runs (no gate claimed)",
                "",
                "| run | steps | final reward | random baseline | note |",
                "|---|---|---|---|---|",
            ]
        )
        for name, r in sorted(partials.items()):
            lines.append(
                f"| {name} | {r['steps']} | {r['final_reward']:.1f} "
                f"| {r['random_baseline']} | {r['note']} |"
            )
        lines.append("")
    (CURVES_DIR / "LEARNING.md").write_text("\n".join(lines))


def main(argv) -> int:
    names = argv or sorted(WORKLOADS)
    CURVES_DIR.mkdir(parents=True, exist_ok=True)
    index_path = CURVES_DIR / "results.json"
    results = json.loads(index_path.read_text()) if index_path.exists() else {}
    for name in names:
        print(f"[learning_curves] running {name} ...", flush=True)
        t0 = time.perf_counter()
        with tempfile.TemporaryDirectory() as tmp:
            rewards, losses = run_workload(name, tmp)
        wall = time.perf_counter() - t0
        (CURVES_DIR / f"{name}.json").write_text(
            json.dumps({"rewards": rewards, "losses": losses}, indent=0)
        )
        plotted = _plot(name, rewards)
        summary = {
            "final_reward": last_quarter_mean(rewards),
            "threshold": WORKLOADS[name]["reward_threshold"],
            "wall_clock_s": wall,
            "points": len(rewards),
            "plotted": plotted,
        }
        results[name] = summary
        print(f"[learning_curves] {name}: {summary}", flush=True)
        # Persist after every workload: a multi-hour suite must not lose the
        # index to a crash in a later workload.
        index_path.write_text(json.dumps(results, indent=2, sort_keys=True) + "\n")
        _write_index(results)
        try:
            check_workload(name, rewards, losses)
            print(f"[learning_curves] {name}: PASS", flush=True)
        except AssertionError as e:
            print(f"[learning_curves] {name}: FAIL — {e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
