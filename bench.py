"""Benchmark harness (driver contract: prints ONE JSON line).

Round-1 benchmark: PPO CartPole-v1 full training wall-clock — BASELINE.json
config #1, the reference's own framework-overhead benchmark
(reference: benchmarks/benchmark.py:1-52 runs exp=ppo_benchmarks and prints
wall-clock; published number: 81.27 s on 4 CPUs, BASELINE.md).

Same workload shape as the reference benchmark: total_steps=65536,
4 envs × 128 rollout steps, logging/checkpoint/test disabled.
``vs_baseline`` > 1 means faster than the reference.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_PPO_CARTPOLE_S = 81.27  # reference v0.5.5, BASELINE.md


def bench_ppo_cartpole() -> dict:
    from sheeprl_tpu.cli import run

    args = [
        "exp=ppo",
        "env.id=CartPole-v1",
        "env.num_envs=4",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.total_steps=65536",
        "algo.rollout_steps=128",
        "algo.run_test=False",
        "metric.log_level=0",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "buffer.memmap=False",
        "print_config=False",
        "log_dir=/tmp/bench_logs",
    ]
    t0 = time.perf_counter()
    run(args)
    elapsed = time.perf_counter() - t0
    return {
        "metric": "ppo_cartpole_65536_steps_wall_clock",
        "value": round(elapsed, 2),
        "unit": "s",
        "vs_baseline": round(BASELINE_PPO_CARTPOLE_S / elapsed, 3),
    }


if __name__ == "__main__":
    result = bench_ppo_cartpole()
    print(json.dumps(result))
