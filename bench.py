"""Benchmark harness (driver contract: prints ONE JSON line).

Default benchmark: **DreamerV3-S gradient-update throughput** — the
north-star workload (BASELINE.json: DreamerV3 Atari-100K).  The reference
trains MsPacman-100K in 14h on an RTX 3080 (BASELINE.md): 100K frames at
action_repeat 4 → 25K policy steps, replay_ratio 1 → ~25K gradient updates,
i.e. ~0.5 updates/s.  Each update processes a 16×64 sequence batch.  This
bench times the SAME work unit — full DreamerV3-S updates (world model +
imagination + actor + critic + EMA) on 64×64×3 pixel sequences — on the
available accelerator, after one warmup dispatch.

``BENCH_TARGET=ppo`` switches to the PPO CartPole wall-clock benchmark
(reference: 81.27 s, BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_DV3_UPDATES_PER_S = 0.5   # RTX 3080, MsPacman-100K (BASELINE.md)

# Reference v0.5.5 published wall-clocks, 4-CPU Lightning Studio host
# (/root/reference/README.md:83-189): exp=<algo>_benchmarks.  The `_wall`
# dreamer targets run the reference's 16384-step tiny-model benchmark config
# (the README "1 device" rows); on hosts without ALE the MsPacman env must be
# swapped via BENCH_ARGS, which voids vs_baseline automatically.
BASELINE_CPU_WALL_CLOCK_S = {
    "ppo": 81.27,            # CartPole-v1, 1 env, 65536 steps
    "a2c": 84.76,            # CartPole-v1, 1 env, 65536 steps
    "sac": 320.21,           # LunarLanderContinuous, 4 envs, 65536 steps
    "dreamer_v1_wall": 2207.13,  # MsPacman tiny model, 16384 steps
    "dreamer_v2_wall": 906.42,
    "dreamer_v3_wall": 1589.30,
}


def _git_sha() -> str | None:
    """The repo HEAD this bench ran against (best-effort — a payload missing
    its SHA is a warning sign, not a crash)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def _bench_stamp(target: str) -> dict:
    """Self-describing provenance every mode stamps into its JSON payload:
    BENCH_*.json files must identify their mode, code revision and
    host/device inventory without consulting the shell history that
    produced them."""
    import multiprocessing
    import platform
    import socket

    stamp = {
        "mode": target,
        "git_sha": _git_sha(),
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "host": {
            "hostname": socket.gethostname(),
            "cpus": multiprocessing.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
        },
    }
    if target == "lint":
        # graftlint is pure host AST work and deliberately skips the bench
        # watchdog — a jax.devices() probe here could hang on a half-wedged
        # TPU tunnel with nothing left to kill it
        stamp["devices"] = None
        return stamp
    try:
        import jax

        devs = jax.devices()
        stamp["devices"] = {
            "count": len(devs),
            "platform": devs[0].platform,
            "kind": getattr(devs[0], "device_kind", ""),
        }
        stamp["jax_version"] = jax.__version__
    except Exception:
        stamp["devices"] = None
    return stamp


def _phase_frac_sum(breakdown: dict) -> float:
    """Σ fractions of a span-window breakdown (the ~1.0 acceptance check)."""
    return round(
        sum(p["frac"] for p in breakdown["phases"].values()) + breakdown["other_frac"], 6
    )


def bench_dreamer_v3() -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric

    size = os.environ.get("BENCH_SIZE", "S")  # smoke-test hook (e.g. XS on CPU)
    overrides = [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=discrete_dummy",
        f"algo=dreamer_v3_{size}",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[]",
        "algo.per_rank_batch_size=16",
        "algo.per_rank_sequence_length=64",
        "fabric.precision=bf16-mixed",
    ]
    # BENCH_MESH='{data: 2, model: 4}': bench on a 2-D (data, model) mesh —
    # the partition-rules sharding path (docs/sharding.md); the mesh shape is
    # stamped into the JSON payload either way
    if os.environ.get("BENCH_MESH"):
        overrides.append(f"fabric.mesh_shape={os.environ['BENCH_MESH']}")
    cfg = compose(overrides)
    fabric = build_fabric(cfg)

    # Build the jitted multi-update train phase exactly as the algorithm does,
    # by reusing its inner machinery through a tiny synthetic replay block.
    L = int(os.environ.get("BENCH_L", 64))
    B = int(os.environ.get("BENCH_B", 16))
    U = int(os.environ.get("BENCH_U", 4))
    rng = np.random.default_rng(0)
    # TPU tiled layout pads the pixel block ~2x (measured: (1024,64,16,64,64,3)
    # u8 allocates 25.8 GiB for 12.9 GiB raw) — refuse shapes whose PER-DEVICE
    # share (the block shards over the mesh) cannot fit HBM next to params,
    # instead of hanging in a doomed compile.  Emitted as a JSON result, not
    # an exception: a raise would make the watchdog misread a deliberate
    # refusal as an accelerator outage and grind the same shape on CPU.
    dev = jax.devices()[0]
    if dev.platform == "tpu":
        hbm = (dev.memory_stats() or {}).get("bytes_limit", 16 * 2**30)
        per_dev = U * L * B * 64 * 64 * 3 * 2.2 / max(len(jax.devices()), 1)
        if per_dev > 0.9 * hbm:
            return {
                "metric": (
                    f"bench_refused: (U={U}, L={L}, B={B}) needs ~{per_dev / 2**30:.1f} GiB "
                    f"padded per device vs {hbm / 2**30:.0f} GiB HBM; reduce BENCH_U/B/L"
                ),
                "value": 0,
                "unit": "",
                "vs_baseline": None,
            }
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }

    train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
    block = fabric.shard_batch(block, axis=2)
    key = jax.random.PRNGKey(0)

    # AOT-compile once through the compile-once layer (make_train_phase now
    # returns an AOTFunction); the SAME executable serves cost_analysis
    # (XLA's own FLOP count — no hand-derived model formula to drift), the
    # warmup and the timed loop, so the heavy train-phase program is never
    # compiled twice.  Fall back to the plain jit wrapper if AOT fails.
    # The compile-vs-steady split is reported as SEPARATE JSON fields
    # (`first_call_s` / `steady_updates_per_s`) so the trajectory can tell a
    # compile-time regression from a math-throughput one.
    # Two FLOPs-per-update estimates feed the MFU line:
    # * XLA's own cost model for the compiled executable (exact for THIS
    #   program, but per-shard under a model axis and backend-dependent);
    # * the analytic param-tree estimate (_dv3_analytic_flops) — derived
    #   from kernel shapes alone, so it is mesh-independent and always
    #   available, including on the CPU fallback where MFU still must be
    #   emitted (ISSUE 7 acceptance).
    flops_per_update = None
    flops_analytic = _dv3_analytic_flops(params, B, L, int(cfg.algo.horizon))
    t_first = time.perf_counter()
    try:
        compiled = train_phase.compile_for(params, opt_state, block, key, jnp.int32(0))
        train_phase = compiled
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        if cost and cost.get("flops"):
            # per-shard flops under a model axis: scale to the whole mesh
            flops_per_update = float(cost["flops"]) * len(fabric.devices) / U
    except Exception:
        pass  # cost analysis is best-effort; the throughput number still stands

    # warmup = first dispatch (compile happens here only on the AOT-fallback
    # path, so first_call_s covers compile + first execution either way).
    # device_sync, NOT block_until_ready: the latter resolves at dispatch on
    # the axon tunnel, which produced the phantom r5 first-capture numbers
    # (BENCH_TPU.md timing-validity note).
    from sheeprl_tpu.utils.utils import device_sync

    params, opt_state, metrics = train_phase(params, opt_state, block, key, jnp.int32(0))
    device_sync((params, metrics))
    first_call_s = time.perf_counter() - t_first

    # Steady-state timed loop runs under jax.transfer_guard("disallow"):
    # every input is device-resident (the block was staged once, above), so
    # ANY implicit H2D inside the window raises and fails the bench — the
    # red/green spelling of the zero-copy claim (`h2d_bytes_per_update`).
    # Counters are pre-staged device scalars for the same reason.
    iters = int(os.environ.get("BENCH_ITERS", 10))
    steps_dev = [jax.device_put(np.int32(i)) for i in range(iters)]
    t0 = time.perf_counter()
    # H2D direction only: D2D resharding (multi-device meshes) is ICI, not
    # host traffic — see data/device_replay.steady_guard
    with jax.transfer_guard_host_to_device("disallow"):
        for i in range(iters):
            params, opt_state, metrics = train_phase(params, opt_state, block, key, steps_dev[i])
    device_sync((params, metrics))
    elapsed = time.perf_counter() - t0
    updates_per_s = (U * iters) / elapsed
    # The RTX-3080 baseline (0.5 updates/s) is for the S model on B=16, L=64
    # pixel batches; any overridden shape is NOT comparable — stamp the real
    # shape into the metric name and only claim vs_baseline when it matches.
    comparable = size == "S" and B == 16 and L == 64
    dev = jax.devices()[0]
    platform = dev.platform
    from sheeprl_tpu.utils.profiler import COMPILE_MONITOR

    n_exe, compile_s = COMPILE_MONITOR.totals()
    result = {
        "metric": (
            f"dreamer_v3_{size}_gradient_updates_per_s "
            f"(B={B} L={L} U={U} pixel batch, {platform})"
        ),
        "value": round(updates_per_s, 3),
        "unit": "updates/s",
        "vs_baseline": round(updates_per_s / BASELINE_DV3_UPDATES_PER_S, 3) if comparable else None,
        # compile-time vs steady-state split (compile-once layer): first_call_s
        # covers AOT lowering+compilation plus the first dispatch; the timed
        # loop above starts only after it, so `value` is pure steady-state
        "first_call_s": round(first_call_s, 3),
        "steady_updates_per_s": round(updates_per_s, 3),
        "compile_executables": n_exe,
        "compile_time_s": round(compile_s, 3),
        # utilization axis (ISSUE 7): mesh topology + FLOPs/update + MFU ride
        # in every payload so BENCH_*.json tracks utilization across rounds.
        # `mfu` uses XLA's cost model when available, `mfu_analytic` the
        # param-tree estimate; both are null (but PRESENT) when the device's
        # peak is unknown (CPU fallback) — override via SHEEPRL_PEAK_FLOPS.
        "mesh_shape": {k: int(v) for k, v in fabric.mesh.shape.items()},
        "flops_per_update": flops_per_update,
        "flops_per_update_analytic": flops_analytic,
        "mfu": None,
        "mfu_analytic": None,
        # zero-copy dataflow axis (ISSUE 9): the timed window ran to
        # completion under jax.transfer_guard("disallow"), so the measured
        # steady state performed zero implicit H2D.  The synthetic block was
        # staged ONCE outside the window; per-update H2D is exactly 0.
        # `replay_hbm_bytes` is reported by `--mode replay`, which times the
        # fused sample+update program over a real DeviceReplay ring.
        "h2d_bytes_per_update": 0.0,
        "replay_hbm_bytes": None,
    }
    peak = _peak_flops_per_s(dev)
    if peak is not None:
        mesh_peak = peak * len(fabric.devices)
        if flops_per_update is not None:
            result["mfu"] = round(flops_per_update * updates_per_s / mesh_peak, 4)
        result["mfu_analytic"] = round(flops_analytic * updates_per_s / mesh_peak, 4)
    return result


def bench_device_replay() -> dict:
    """Zero-copy replay dataflow bench (``--mode replay``, ISSUE 9).

    Builds a real :class:`~sheeprl_tpu.data.device_replay.DeviceReplay`
    ring (DreamerV3-XS-shaped pixel data by default), appends through the
    donated-write path, then times the FUSED on-device sample+update
    program — sequence-index generation, ring gather and the full DV3 train
    phase in one AOT executable — with ``jax.transfer_guard("disallow")``
    armed over the whole steady window.  ``h2d_bytes_per_update`` is 0 by
    construction and the guard makes that a hard assertion rather than
    prose; ``replay_hbm_bytes`` reports the resident ring footprint.
    ``BENCH_REPLAY_MODE=uniform`` times the uniform-sampling gather path
    (the SAC family's dataflow) with a summing consumer instead of the
    dreamer update — isolating replay dataflow from model math.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.data.device_replay import DeviceReplay, fused_sequence_train
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.utils.utils import device_sync, merge_framestack  # noqa: F401

    size = os.environ.get("BENCH_SIZE", "XS")
    L = int(os.environ.get("BENCH_L", 8))
    B = int(os.environ.get("BENCH_B", 4))
    U = int(os.environ.get("BENCH_U", 2))
    n_envs = int(os.environ.get("BENCH_ENVS", 4))
    window = int(os.environ.get("BENCH_REPLAY_WINDOW", 512))
    iters = int(os.environ.get("BENCH_ITERS", 10))
    mode = os.environ.get("BENCH_REPLAY_MODE", "sequence")

    cfg = compose(
        [
            "exp=dreamer_v3", "env=dummy", "env.id=discrete_dummy",
            f"algo=dreamer_v3_{size}",
            "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[]",
            f"algo.per_rank_batch_size={B}",
            f"algo.per_rank_sequence_length={L}",
        ]
    )
    fabric = build_fabric(cfg)
    rb = DeviceReplay(window, n_envs, mesh=fabric.mesh, data_axis=fabric.data_axis)
    rng = np.random.default_rng(0)
    # fill through the donated append path (what the actor loop does)
    chunk = 32
    for _ in range(window // chunk):
        rb.add({
            "rgb": rng.integers(0, 255, (chunk, n_envs, 64, 64, 3)).astype(np.uint8),
            "actions": rng.integers(0, 2, (chunk, n_envs, 4)).astype(np.float32),
            "rewards": rng.normal(size=(chunk, n_envs, 1)).astype(np.float32),
            "terminated": np.zeros((chunk, n_envs, 1), np.float32),
            "is_first": np.zeros((chunk, n_envs, 1), np.float32),
        })

    key = jax.random.PRNGKey(0)
    if mode == "uniform":
        def consume(p, o, batch, k, counter):
            s = sum(jnp.sum(v.astype(jnp.float32)) for v in batch.values())
            return p + 0.0 * s, o, s

        from sheeprl_tpu.data.device_replay import fused_uniform_train

        fused = fused_uniform_train(
            fabric, consume, rb, batch_size=B * L, prep=lambda b: b, name="bench.replay_uniform"
        )
        params = jax.device_put(jnp.zeros(()))
        opt_state = jax.device_put(jnp.zeros(()))
    else:
        def _prep(b):
            return {
                "rgb": b["rgb"],
                "actions": b["actions"],
                "rewards": b["rewards"][..., 0],
                "terminated": b["terminated"][..., 0],
                "is_first": b["is_first"][..., 0],
            }

        train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
        fused = fused_sequence_train(
            fabric, train_phase, rb, B, L, _prep, name="bench.replay_sequence"
        )

    counter = jax.device_put(np.int32(0))
    # warmup (compile) dispatch
    t_first = time.perf_counter()
    params, opt_state, counter, metrics = fused(
        params, opt_state, rb.buffers, rb.cursor, key, counter, n_samples=U
    )
    device_sync((params, metrics))
    first_call_s = time.perf_counter() - t_first

    # pre-split OUTSIDE the guard: eager `keys[i]` slicing stages its index
    # as an implicit device scalar, which the guard (correctly) rejects
    keys = list(jax.random.split(key, iters))
    # span-instrumented steady window (telemetry/spans.py): the fused
    # program is on-device sampling + update in ONE executable, so its whole
    # dispatch is the update.dispatch phase; the breakdown's fractions must
    # sum to ~1.0 (acceptance)
    from sheeprl_tpu.telemetry.spans import SPANS, span

    SPANS.roll_window()
    t0 = time.perf_counter()
    with jax.transfer_guard_host_to_device("disallow"):
        for i in range(iters):
            with span("update.dispatch"):
                params, opt_state, counter, metrics = fused(
                    params, opt_state, rb.buffers, rb.cursor, keys[i], counter, n_samples=U
                )
    device_sync((params, metrics))
    elapsed = time.perf_counter() - t0
    phase_breakdown = SPANS.breakdown()

    dev = jax.devices()[0]
    return {
        "metric": (
            f"device_replay_{mode}_updates_per_s "
            f"(dv3_{size} B={B} L={L} U={U} window={window}x{n_envs}, {dev.platform})"
        ),
        "value": round(U * iters / elapsed, 3),
        "unit": "updates/s",
        "vs_baseline": None,
        "first_call_s": round(first_call_s, 3),
        "steady_updates_per_s": round(U * iters / elapsed, 3),
        # the guard completing IS the measurement: zero implicit H2D in the
        # steady window, so per-update H2D bytes are exactly 0
        "h2d_bytes_per_update": 0.0,
        "replay_hbm_bytes": rb.hbm_bytes,
        "mesh_shape": {k: int(v) for k, v in fabric.mesh.shape.items()},
        "phase_breakdown": phase_breakdown,
        "phase_frac_sum": _phase_frac_sum(phase_breakdown),
    }


def _dv3_analytic_flops(params, batch: int, seq_len: int, horizon: int) -> float:
    """Analytic FLOPs per gradient update from the param tree alone.

    Purpose: a mesh- and backend-independent MFU denominator that cannot
    silently change when the compiled program does (the 8.8% -> >=25% claim
    must be measured against a fixed cost model).  It is an independent
    cross-check of XLA's per-executable count, not a replica of it: XLA
    sees the post-optimization HLO (and its CPU cost model is known to
    count convolutions differently), so the two can differ by ~2x on tiny
    presets — `mfu` (XLA) is primary when the backend provides it,
    `mfu_analytic` is the always-available, never-silently-changing one.

    Cost model (per token, fwd = 2*prod(kernel) MACs; train = 3x fwd for
    forward + both backward matmuls):

    * world-model phase (encoder, RSSM scan, decoder, reward/continue
      heads): every kernel trains on B*L sequence tokens;
    * imagination phase: the RSSM dynamics (recurrent+transition) and the
      actor roll `horizon` steps from B*L start states — the dynamics are
      forward-only under DreamerV3's straight-through/REINFORCE estimator
      (1x), the actor trains (3x);
    * critic + target critic evaluate horizon+1 imagined states: critic
      trains (3x), the EMA target is forward-only (1x).

    Conv/deconv kernels are weighted by their spatial position count in the
    64x64 stride-2 pyramid (conv_i at (32/2^i)^2 positions, deconv_i
    mirrored, the final RGB deconv at 64^2); dense kernels count once per
    token.
    """
    import re as _re

    import jax
    import numpy as _np
    from jax.tree_util import tree_flatten_with_path

    def kernel_fwd_flops(tree) -> float:
        flat, _ = tree_flatten_with_path(tree)
        total = 0.0
        for kp, leaf in flat:
            if getattr(leaf, "ndim", 0) < 2:
                continue
            path = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in kp)
            macs = float(_np.prod(leaf.shape))
            m = _re.search(r"(de)?conv_(\d+)|deconv_out", path)
            if m and leaf.ndim == 4:
                if "deconv_out" in path:
                    positions = 64 * 64
                elif m.group(1):  # deconv_i: 4x4 latent grid upsampled 2x per layer
                    positions = (4 * 2 ** (int(m.group(2)) + 1)) ** 2
                else:  # conv_i: 64x64 downsampled 2x per layer
                    positions = (64 // 2 ** (int(m.group(2)) + 1)) ** 2
                macs *= positions
            total += 2.0 * macs
        return total

    p = params if isinstance(params, dict) else jax.device_get(params)
    tokens = float(batch * seq_len)
    wm = kernel_fwd_flops(p.get("world_model", {}))
    actor = kernel_fwd_flops(p.get("actor", {}))
    critic = kernel_fwd_flops(p.get("critic", {}))
    target = kernel_fwd_flops(p.get("target_critic", {}))
    dyn = kernel_fwd_flops(
        {
            k: v
            for k, v in (p.get("world_model", {}).get("params", {}) or {}).items()
            if k in ("recurrent_model", "transition_model")
        }
    )
    return (
        3.0 * tokens * wm
        + tokens * horizon * (dyn + 3.0 * actor)
        + tokens * (horizon + 1) * (3.0 * critic + target)
    )


def _peak_flops_per_s(dev) -> float | None:
    """Peak bf16 FLOPs/s PER DEVICE for known TPU generations (public spec
    sheets); None when unknown (CPU fallback) so MFU is never reported
    against a made-up denominator.  ``SHEEPRL_PEAK_FLOPS`` overrides —
    the hook for emitting a numeric MFU on hosts the table doesn't know."""
    env = os.environ.get("SHEEPRL_PEAK_FLOPS", "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            # a typo'd override must not throw away a finished multi-minute
            # bench (and a raise here reads as an accelerator outage to the
            # watchdog) — fall back to the device table
            print(f"[bench] ignoring malformed SHEEPRL_PEAK_FLOPS={env!r}", file=sys.stderr)
    kind = getattr(dev, "device_kind", "").lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12, "v6e": 918e12,
    }
    for name, peak in table.items():
        if name in kind:
            return peak
    return None


def _build_dv3_train_phase(fabric, cfg):
    """Construct DreamerV3 modules + the single-dispatch train phase the
    training script uses, against a synthetic Dict observation space."""
    import numpy as np
    from gymnasium import spaces

    import jax

    from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as dv3

    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})

    # reuse the module-level pieces by instantiating a miniature "main"
    # closure: we inline the same construction path
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_dv3_optimizers

    world_model, actor, critic, params = build_agent(fabric, (4,), False, cfg, obs_space)
    wm_opt, actor_opt, critic_opt, opt_state = build_dv3_optimizers(fabric, cfg, params)
    # params/opt_state pin the partition-rules state shardings on the program
    # exactly as the training loop does — the benchmarked program IS the
    # training program, mesh topology included
    train_phase = dv3.make_train_phase(
        fabric, cfg, world_model, actor, critic, wm_opt, actor_opt, critic_opt,
        cnn_keys=("rgb",), mlp_keys=(), is_continuous=False,
        params=params, opt_state=opt_state,
    )
    return train_phase, params, opt_state


def bench_cpu_wall_clock(algo: str) -> dict:
    """Run the EXACT reference benchmark workload (exp=<algo>_benchmarks —
    same env, env count, rollout/batch shapes and step budget as the
    reference's published run, logging and test disabled) end-to-end and
    report wall-clock vs the reference's published 4-CPU number
    (/root/reference/README.md:83-189: 65536 steps for ppo/a2c/sac, 16384
    for the tiny-model dreamer rows)."""
    import multiprocessing

    from sheeprl_tpu.cli import run
    from sheeprl_tpu.config.compose import compose

    # BENCH_ARGS: extra CLI overrides, stamped into the metric name so a
    # modified workload can never masquerade as the reference one
    extra = os.environ.get("BENCH_ARGS", "").split()
    exp = algo.removesuffix("_wall")
    args = [
        f"exp={exp}_benchmarks",
        "print_config=False",
        "log_dir=/tmp/bench_logs",
        *extra,
    ]
    # the step count comes from the composed workload itself, never a
    # hardcoded constant that could drift from the exp config
    steps = int(compose(args).algo.total_steps)
    t0 = time.perf_counter()
    run(args)
    elapsed = time.perf_counter() - t0
    ncpu = multiprocessing.cpu_count()
    label = f" [{' '.join(extra)}]" if extra else ""
    if os.environ.get("BENCH_ON_ACCEL"):
        import jax

        host = f"1x {jax.devices()[0].device_kind} vs 4-CPU baseline"
    else:
        host = f"{ncpu}-core host vs 4-CPU baseline"
    return {
        "metric": f"{exp}_benchmarks_{steps}_steps_wall_clock ({host}){label}",
        "value": round(elapsed, 2),
        "unit": "s",
        # vs_baseline only for the untouched reference workload — a modified
        # one gets the bracketed label and no numeric comparison
        "vs_baseline": round(BASELINE_CPU_WALL_CLOCK_S[algo] / elapsed, 3) if not extra else None,
    }


def _tiny_serve_ckpt(algo: str, prefix: str = "bench_serve_") -> str:
    """A committed tiny-dryrun checkpoint to serve from (shared by the
    ``serve`` and ``serve_fleet`` benches)."""
    import tempfile

    from sheeprl_tpu.cli import run
    from tests.ckpt_utils import find_checkpoints

    log_dir = tempfile.mkdtemp(prefix=prefix)
    env_id = "continuous_dummy" if algo.startswith("sac") else "discrete_dummy"
    args = [
        f"exp={algo}", "env=dummy", f"env.id={env_id}", "dry_run=True",
        "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
        "fabric.devices=1", "metric.log_level=0", "checkpoint.every=1",
        "buffer.memmap=False", "algo.learning_starts=0",
        f"log_dir={log_dir}", "print_config=False", "algo.run_test=False",
    ]
    if algo == "dreamer_v3":
        args += [
            "algo=dreamer_v3_XS", "algo.per_rank_batch_size=2",
            "algo.per_rank_sequence_length=8", "algo.horizon=4",
            "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[state]",
            "algo.world_model.encoder.cnn_channels_multiplier=4",
            "algo.dense_units=16",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.transition_model.hidden_size=16",
            "algo.world_model.representation_model.hidden_size=16",
        ]
    run(args)
    return str(find_checkpoints(log_dir)[-1])


def bench_serve() -> dict:
    """Policy-as-a-service load benchmark (``--mode serve``).

    Stands up a :class:`~sheeprl_tpu.serve.service.PolicyService` on a
    committed checkpoint (``BENCH_SERVE_CKPT``, or a fresh tiny dryrun of
    ``BENCH_SERVE_ALGO``, default ppo), then ``BENCH_SERVE_CLIENTS``
    threads each stream ``BENCH_SERVE_REQUESTS`` blocking act() calls
    through the continuous batcher.  Reports steady-state **actions/s**
    plus the latency percentiles (p50/p99 ms) and the compile counters —
    ``steady_compiles`` must be 0: the batch ladder is AOT-warmed before
    the timed window, so a nonzero value means a shape escaped the ladder.
    """
    import threading

    import numpy as np

    algo = os.environ.get("BENCH_SERVE_ALGO", "ppo")
    ckpt = os.environ.get("BENCH_SERVE_CKPT") or _tiny_serve_ckpt(algo)

    from sheeprl_tpu.serve import PolicyService
    from sheeprl_tpu.utils.profiler import COMPILE_MONITOR

    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 16))
    per_client = int(os.environ.get("BENCH_SERVE_REQUESTS", 64))
    service = PolicyService.from_checkpoint(ckpt, ["serve.watch_commits=False"])
    service.start()  # warms the whole batch ladder before returning
    obs = {
        k: np.zeros(shape, np.dtype(dt))
        for k, (shape, dt) in service.player.obs_spec.items()
    }
    # settle the pipeline outside the timed window (first dispatches mix in
    # host-side warmup noise), then snapshot the compile counter: any compile
    # during the timed window is a ladder escape
    for _ in range(4):
        service.act(obs, timeout=60.0)
    exe_before, _ = COMPILE_MONITOR.totals()
    service.latency = type(service.latency)(int(clients * per_client * 1.1))

    barrier = threading.Barrier(clients + 1)
    errors: list = []

    def worker(wid: int) -> None:
        barrier.wait()
        for _ in range(per_client):
            try:
                service.act(obs, session=f"bench-{wid}", timeout=120.0)
            except Exception as e:  # count, don't crash the bench
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    exe_after, compile_s = COMPILE_MONITOR.totals()
    total = clients * per_client - len(errors)
    stats = service.stats()
    service.stop()
    import jax

    return {
        "metric": (
            f"serve_{algo}_actions_per_s "
            f"({clients} clients x {per_client} reqs, "
            f"ladder {stats['batch_ladder']}, {jax.devices()[0].platform})"
        ),
        "value": round(total / elapsed, 3),
        "unit": "actions/s",
        "vs_baseline": None,
        "p50_ms": round(stats["p50_ms"], 3),
        "p99_ms": round(stats["p99_ms"], 3),
        "avg_batch": stats["avg_batch"],
        "padded_frac": stats["padded_frac"],
        "serve_errors": len(errors),
        "steady_compiles": exe_after - exe_before,
        "compile_executables": exe_after,
        "compile_time_s": round(compile_s, 3),
    }


def bench_serve_fleet() -> dict:
    """Fault-tolerant serving-fleet benchmark (``--mode serve_fleet``,
    ISSUE 17).

    Three phases over REAL replica processes (``LocalFleet`` spawning
    ``python -m sheeprl_tpu.serve``) behind a ``FleetRouter`` front:

    * **A (baseline)** — a 1-replica fleet under ``BENCH_FLEET_CLIENTS``
      threads x ``BENCH_FLEET_REQUESTS`` acts: the router-included
      single-replica actions/s;
    * **B (scaling)** — the same load over ``BENCH_FLEET_REPLICAS``
      replicas; per-replica efficiency = thr_R / (thr_1 * R) must reach
      ``BENCH_FLEET_SCALE_FLOOR`` (default 0.8);
    * **C (chaos)** — the same fleet with one replica SIGKILLed
      mid-window: zero dropped requests, every session completes.

    ``gate_failed`` on any drop, any lost session, or sub-floor scaling.
    """
    import signal
    import threading

    import numpy as np

    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.fleet import FleetRouter, FleetServer, LocalFleet

    algo = os.environ.get("BENCH_SERVE_ALGO", "ppo")
    ckpt = os.environ.get("BENCH_SERVE_CKPT") or _tiny_serve_ckpt(algo, "bench_fleet_")
    replicas = max(2, int(os.environ.get("BENCH_FLEET_REPLICAS", 2)))
    clients = int(os.environ.get("BENCH_FLEET_CLIENTS", 16))
    per_client = int(os.environ.get("BENCH_FLEET_REQUESTS", 64))
    floor = float(os.environ.get("BENCH_FLEET_SCALE_FLOOR", 0.8))
    cfg = {"serve": {"fleet": {"health_poll_s": 0.2, "eject_threshold": 2, "readmit_s": 0.5}}}
    overrides = ["serve.batch_ladder=[1,8,16]", "serve.max_wait_ms=2"]

    def run_load(url: str, kill_after_s: float = -1.0, fleet=None, sessions=False):
        """(elapsed_s, completed_sessions, errors) for one client storm.

        Scaling phases run sessionless (least-loaded dispatch spreads the
        load evenly); the chaos phase runs session-bearing so the kill also
        exercises sticky re-routing and session completion."""
        barrier = threading.Barrier(clients + 1)
        done: list = []
        errors: list = []

        def worker(wid: int) -> None:
            client = PolicyClient(url, timeout=120.0, retries=8, retry_base_s=0.2)
            session = f"bench-{wid}" if sessions else None
            barrier.wait(timeout=300.0)
            try:
                for _ in range(per_client):
                    client.act(obs, greedy=True, session=session)
                done.append(wid)
            except Exception as e:  # the gate IS "no exception"
                errors.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(clients)]
        for t in threads:
            t.start()
        barrier.wait(timeout=300.0)
        t0 = time.perf_counter()
        if kill_after_s >= 0:
            killer = threading.Timer(
                kill_after_s, lambda: fleet.kill(0, sig=signal.SIGKILL)
            )
            killer.start()
        for t in threads:
            t.join(600.0)
        elapsed = time.perf_counter() - t0
        return elapsed, len(done), errors

    results: dict = {}
    total = clients * per_client
    for phase, n in (("single", 1), ("fleet", replicas)):
        fleet = LocalFleet(
            ckpt, overrides=overrides, replicas=n,
            backoff_base_s=0.2, backoff_max_s=1.0, echo=False,
        )
        fleet.start()
        server = None
        try:
            router = FleetRouter(fleet.addresses(), cfg)
            fleet.attach(router)
            server = FleetServer(router)
            server.start()
            if not router.wait_healthy(min_replicas=n, timeout=300.0):
                raise RuntimeError(f"{phase}: fleet never became healthy: {router.health()}")
            health = PolicyClient(server.url, timeout=120.0).health()
            obs = {
                k: np.zeros(shape, np.dtype(dt))
                for k, (shape, dt) in health["obs_spec"].items()
            }
            run_load(server.url)  # settle: warm every replica + HTTP path
            elapsed, completed, errors = run_load(server.url)
            results[phase] = {
                "actions_per_s": round(total / elapsed, 3),
                "elapsed_s": round(elapsed, 3),
                "completed_sessions": completed,
                "dropped": len(errors),
                "errors": errors[:3],
            }
            if phase == "fleet":
                # phase C on the same fleet: kill a replica mid-window
                elapsed, completed, errors = run_load(
                    server.url,
                    kill_after_s=max(0.3, elapsed / 4),
                    fleet=fleet,
                    sessions=True,
                )
                stats = router.stats()
                results["chaos"] = {
                    "actions_per_s": round(total / elapsed, 3),
                    "completed_sessions": completed,
                    "dropped": len(errors),
                    "errors": errors[:3],
                    "failovers": stats["failovers"],
                    "ejects": stats["ejects"],
                    "respawns": stats["respawns"],
                }
        finally:
            if server is not None:
                server.stop()
            fleet.stop()

    thr_1 = results["single"]["actions_per_s"]
    thr_r = results["fleet"]["actions_per_s"]
    efficiency = thr_r / (thr_1 * replicas) if thr_1 > 0 else 0.0
    dropped = sum(results[p]["dropped"] for p in results)
    lost_sessions = sum(clients - results[p]["completed_sessions"] for p in results)
    # the scaling gate needs a host that can actually back R replica
    # processes plus the router: on fewer cores linear scaling is
    # physically impossible, so efficiency is reported but not gated
    cores = os.cpu_count() or 1
    scale_gated = cores >= replicas + 1
    gate_failed = (
        dropped > 0 or lost_sessions > 0 or (scale_gated and efficiency < floor)
    )
    label = "" if scale_gated else f" [scaling ungated: {cores} cpus for {replicas} replicas]"
    return {
        "metric": (
            f"serve_fleet_{algo}_actions_per_s "
            f"({replicas} replicas, {clients} clients x {per_client} reqs, "
            f"SIGKILL chaos phase){label}"
        ),
        "value": thr_r,
        "unit": "actions/s",
        "vs_baseline": None,
        "single_replica_actions_per_s": thr_1,
        "scaling_efficiency_per_replica": round(efficiency, 3),
        "scale_floor": floor,
        "scale_gated": scale_gated,
        "dropped_requests": dropped,
        "lost_sessions": lost_sessions,
        "phases": results,
        "gate_failed": gate_failed,
    }


def bench_env() -> dict:
    """Env-stepping throughput axis (``--mode env`` / ``BENCH_TARGET=env``,
    ISSUE 11): env-steps/s for the three rollout dataflows on CartPole-class
    dynamics —

    * ``cpu_gym_async`` — gymnasium ``AsyncVectorEnv`` over CPU gym
      processes (the historical path; the BENCH_TPU.md honest negative);
    * ``jax_adapter`` — the same pure-JAX env stepped one jitted program
      per step through ``JaxToGymAdapter`` + ``SyncVectorEnv`` (the
      compatibility path every algo can use);
    * ``jax_fused`` — the Anakin dataflow: ONE jitted ``lax.scan`` over the
      batched in-trace env step (``VectorJaxEnv``), thousands of instances
      per dispatch, zero host round-trips.

    Actions are pre-sampled/constant so the axis isolates env dataflow from
    policy math.  The fused number uses many more instances on purpose —
    batch scale IS the Anakin win; per-path env counts are reported.
    """
    import numpy as np

    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.envs.jax.adapter import JaxToGymAdapter
    from sheeprl_tpu.envs.jax.core import VectorJaxEnv
    from sheeprl_tpu.envs.jax.registry import make_jax_env

    n_async = int(os.environ.get("BENCH_ENVS", 16))
    n_fused = int(os.environ.get("BENCH_FUSED_ENVS", 1024))
    steps = int(os.environ.get("BENCH_ENV_STEPS", 512))
    fused_iters = int(os.environ.get("BENCH_ENV_ITERS", 8))

    rng = np.random.default_rng(0)
    actions = rng.integers(0, 2, (steps, n_async)).astype(np.int64)

    # ---- cpu gym async (the AsyncVectorEnv baseline) ----------------------
    venv = gym.vector.AsyncVectorEnv(
        [lambda: gym.make("CartPole-v1") for _ in range(n_async)]
    )
    venv.reset(seed=0)
    # one warm step outside the timer (worker spin-up)
    venv.step(actions[0])
    t0 = time.perf_counter()
    for i in range(steps):
        venv.step(actions[i])
    cpu_gym_rate = steps * n_async / (time.perf_counter() - t0)
    venv.close()

    # ---- jax adapter through SyncVectorEnv (its shipped path) -------------
    senv = gym.vector.SyncVectorEnv(
        [lambda: JaxToGymAdapter(make_jax_env("cartpole")) for _ in range(n_async)]
    )
    senv.reset(seed=0)
    senv.step(actions[0])
    t0 = time.perf_counter()
    for i in range(steps):
        senv.step(actions[i])
    adapter_rate = steps * n_async / (time.perf_counter() - t0)
    senv.close()

    # ---- Anakin fused scan -------------------------------------------------
    fused_env = VectorJaxEnv(make_jax_env("cartpole"), n_fused)

    def fused_rollout(state, key):
        def body(carry, k):
            state = carry
            acts = jax.random.bernoulli(k, shape=(n_fused,)).astype(jnp.int32)
            state, _, reward, term, trunc, _ = fused_env.step(state, acts)
            return state, reward

        state, rewards = jax.lax.scan(body, state, jax.random.split(key, steps))
        return state, jnp.sum(rewards)

    fused_rollout = jax.jit(fused_rollout, donate_argnums=(0,))
    state, _ = fused_env.reset(jax.random.PRNGKey(0))
    t_first = time.perf_counter()
    state, s = fused_rollout(state, jax.random.PRNGKey(1))
    s.block_until_ready()
    first_call_s = time.perf_counter() - t_first
    keys = list(jax.random.split(jax.random.PRNGKey(2), fused_iters))
    from sheeprl_tpu.telemetry.spans import SPANS, span

    SPANS.roll_window()
    t0 = time.perf_counter()
    with jax.transfer_guard_host_to_device("disallow"):
        for i in range(fused_iters):
            with span("rollout"):
                state, s = fused_rollout(state, keys[i])
    s.block_until_ready()
    fused_rate = steps * n_fused * fused_iters / (time.perf_counter() - t0)
    phase_breakdown = SPANS.breakdown()

    dev = jax.devices()[0]
    return {
        "metric": (
            f"env_steps_per_s (cartpole: cpu-gym async x{n_async} vs jax adapter "
            f"x{n_async} vs jax fused x{n_fused}, {dev.platform})"
        ),
        "value": round(fused_rate, 1),
        "unit": "env_steps/s",
        # the acceptance comparison: fused Anakin rollout vs the
        # AsyncVectorEnv cpu-gym baseline on this host
        "vs_baseline": round(fused_rate / cpu_gym_rate, 2),
        "env_steps_per_s_cpu_gym_async": round(cpu_gym_rate, 1),
        "env_steps_per_s_jax_adapter": round(adapter_rate, 1),
        "env_steps_per_s_jax_fused": round(fused_rate, 1),
        "n_envs_async": n_async,
        "n_envs_fused": n_fused,
        "first_call_s": round(first_call_s, 3),
        # guard completion == zero H2D inside the fused steady loop
        "h2d_bytes_per_update": 0.0,
        "phase_breakdown": phase_breakdown,
        "phase_frac_sum": _phase_frac_sum(phase_breakdown),
    }


def bench_population() -> dict:
    """Population-axis scaling bench (``--mode population`` /
    ``BENCH_TARGET=population``, ISSUE 20): per-member env-steps/s of a
    population=P CartPole phase — rollout + policy-gradient update + the
    in-trace PBT exploit/explore gate, vmapped over P members inside ONE
    donated-carry fused executable — against the SAME member phase compiled
    single-agent.

    ``per_member_scaling = (pop_rate / P) / single_rate``: the fraction of
    a lone agent's throughput each population member retains.  GATES the
    ISSUE 20 acceptance: ``per_member_scaling >= 0.7 x hardware-ideal`` at
    P=4 (training 4 members together must cost well under 4 sequential
    runs — the batched population is the point) and ``steady_compiles ==
    0`` with both executables at ``cache_size() == 1`` under the armed
    transfer guard (``h2d_bytes_per_update == 0`` by guard completion).

    The hardware-ideal term keeps the gate honest across hosts: on an
    accelerator (or any host with >= P cores) ideal is 1.0 and the gate is
    the plain ``>= 0.7``; on an N-core CPU host with N < P the members'
    compute genuinely serializes, so ideal degrades to ``N / P`` — the
    gate then measures the vmap/PBT machinery's *overhead* rather than
    penalizing the host for lacking parallel compute units.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import sample_actions
    from sheeprl_tpu.envs.jax.anakin import make_rollout_fn
    from sheeprl_tpu.envs.jax.cartpole import JaxCartPole
    from sheeprl_tpu.envs.jax.core import VectorJaxEnv
    from sheeprl_tpu.parallel.fabric import Fabric
    from sheeprl_tpu.population import (
        PBTConfig,
        init_population_state,
        make_population_phase,
        tile_stack,
    )
    from sheeprl_tpu.utils.profiler import COMPILE_MONITOR
    from sheeprl_tpu.utils.structured import dotdict
    from sheeprl_tpu.utils.utils import device_sync

    pop_size = int(os.environ.get("BENCH_POP_SIZE", 4))
    num_envs = int(os.environ.get("BENCH_POP_ENVS", 64))
    rollout_steps = int(os.environ.get("BENCH_POP_ROLLOUT", 32))
    iters = int(os.environ.get("BENCH_POP_ITERS", 16))

    fabric = Fabric(devices=1)
    venv = VectorJaxEnv(JaxCartPole(), num_envs)

    def apply(p, obs):
        h = jnp.tanh(obs["state"] @ p["w1"]) @ p["w2"]
        return h[..., :2], h[..., 2:3]

    rollout_fn = make_rollout_fn(
        venv,
        apply,
        lambda out, k: sample_actions(out, (2,), False, k),
        cnn_keys=(),
        mlp_keys=("state",),
        action_space=venv.single_action_space,
        gamma=0.99,
        rollout_steps=rollout_steps,
    )

    def pg_loss(p, traj):
        # one-step PG surrogate + value regression: a real gradient through
        # the policy net, small enough that env stepping stays the axis
        logits, value = apply(p, traj)
        logp = jax.nn.log_softmax(logits)
        act = traj["actions"][..., 0].astype(jnp.int32)
        chosen = jnp.take_along_axis(logp, act[..., None], axis=-1)[..., 0]
        adv = traj["rewards"] - jax.lax.stop_gradient(value[..., 0])
        return (-chosen * adv).mean() + 0.5 * ((value[..., 0] - traj["rewards"]) ** 2).mean()

    def member_phase(p, o_state, actor, k, hp):
        actor, traj, last_obs, stats = rollout_fn(p, actor, k)
        grads = jax.grad(pg_loss)(p, traj)
        p = jax.tree.map(lambda w, g: w - hp["lr"] * g, p, grads)
        o_state = jax.tree.map(lambda m, g: 0.9 * m + g, o_state, grads)
        return p, o_state, actor, (jnp.zeros(()),), stats

    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": 0.1 * jax.random.normal(k1, (4, 32), jnp.float32),
            "w2": 0.1 * jax.random.normal(k2, (32, 3), jnp.float32),
        }

    def init_actor(key):
        env_state, _ = venv.reset(key)
        return {
            "env": env_state,
            "ep_ret": jnp.zeros((num_envs,), jnp.float32),
            "ep_len": jnp.zeros((num_envs,), jnp.int32),
            "update": jnp.zeros((), jnp.int32),
        }

    pbt_cfg = PBTConfig.from_cfg(
        dotdict(
            {
                "population": dict(
                    size=pop_size, exploit_every=5, warmup=2, frac=0.25,
                    perturb_min=0.8, perturb_max=1.25, init_min=0.5,
                    init_max=2.0, bound_min=0.05, bound_max=20.0,
                    fitness_alpha=0.3, levels=None,
                )
            }
        ),
        base={"lr": 1e-2},
    )

    def _measure(step_fn, args, env_steps_per_iter, keep=None):
        # `keep`: how many leading outputs feed back as the next call's args
        # (the population phase also returns losses/stats, which don't)
        t0 = time.perf_counter()
        args = step_fn(*args)[:keep]
        device_sync(args)
        first_call_s = time.perf_counter() - t0
        n0, _ = COMPILE_MONITOR.totals()
        t0 = time.perf_counter()
        with jax.transfer_guard_host_to_device("disallow"):
            for _ in range(iters):
                args = step_fn(*args)[:keep]
        device_sync(args)
        wall = time.perf_counter() - t0
        n1, _ = COMPILE_MONITOR.totals()
        return {
            "rate": env_steps_per_iter * iters / wall,
            "first_call_s": first_call_s,
            "steady_compiles": n1 - n0,
            "cache_size": step_fn.cache_size(),
        }

    # ---- single-agent Anakin arm (fixed hyperparams baked in) -------------
    single_hp = {"lr": jnp.float32(1e-2)}

    def single_fused(p, o_state, actor, k):
        k, k_m = jax.random.split(k)
        p, o_state, actor, _, _ = member_phase(p, o_state, actor, k_m, single_hp)
        return p, o_state, actor, k

    single_step = fabric.compile(
        single_fused, name="bench.population.single", donate_argnums=(0, 1, 2)
    )
    params1 = fabric.replicate(init_params(jax.random.PRNGKey(0)))
    opt1 = jax.tree.map(jnp.zeros_like, params1)
    single = _measure(
        single_step,
        (params1, opt1, init_actor(jax.random.PRNGKey(1)), jax.random.PRNGKey(2)),
        num_envs * rollout_steps,
    )

    # ---- population arm (P members + in-trace PBT, one executable) --------
    population_step = fabric.compile(
        make_population_phase(member_phase, pbt_cfg),
        name="bench.population.phase",
        donate_argnums=(0, 1, 2, 3),
    )
    params = jax.vmap(init_params)(jax.random.split(jax.random.PRNGKey(0), pop_size))
    opt = jax.tree.map(jnp.zeros_like, params)
    members = jax.vmap(init_actor)(jax.random.split(jax.random.PRNGKey(1), pop_size))
    pop_state = init_population_state(members, pbt_cfg, num_envs)
    hp = pbt_cfg.init_hyperparams(jax.random.PRNGKey(3))
    pop = _measure(
        population_step,
        (params, opt, pop_state, hp, jax.random.PRNGKey(4)),
        pop_size * num_envs * rollout_steps,
        keep=5,
    )

    per_member_rate = pop["rate"] / pop_size
    scaling = per_member_rate / single["rate"]
    steady_compiles = single["steady_compiles"] + pop["steady_compiles"]
    cache_ok = single["cache_size"] == 1 and pop["cache_size"] == 1
    dev = jax.devices()[0]
    ideal = min(1.0, (os.cpu_count() or 1) / pop_size) if dev.platform == "cpu" else 1.0
    scaling_floor = 0.7 * ideal
    return {
        "metric": (
            f"per_member_env_steps_per_s (cartpole pop={pop_size} x{num_envs} envs "
            f"vs single-agent anakin, {dev.platform})"
        ),
        "value": round(per_member_rate, 1),
        "unit": "env_steps/s",
        "per_member_scaling": round(scaling, 3),
        "per_member_scaling_floor": round(scaling_floor, 3),
        "env_steps_per_s_single": round(single["rate"], 1),
        "env_steps_per_s_population_total": round(pop["rate"], 1),
        "population_size": pop_size,
        "n_envs_per_member": num_envs,
        "first_call_s_single": round(single["first_call_s"], 3),
        "first_call_s_population": round(pop["first_call_s"], 3),
        "steady_compiles": steady_compiles,
        "cache_size_single": single["cache_size"],
        "cache_size_population": pop["cache_size"],
        # guard completion over every steady window == zero H2D
        "h2d_bytes_per_update": 0.0,
        "gate_failed": not (scaling >= scaling_floor and steady_compiles == 0 and cache_ok),
    }


def bench_sebulba() -> dict:
    """Sebulba actor–learner topology bench (``--mode sebulba``, ISSUE 12).

    Two measured runs of decoupled PPO on jax CartPole:

    * **adapter-path decoupled baseline** — the pipelined single-controller
      ``ppo_decoupled`` stepping the jax env through ``JaxToGymAdapter``
      (the pre-Sebulba dataflow);
    * **sebulba** — the device-group split (``topology=sebulba``): fused
      jax-env rollout shards on the actor devices, the learner sub-mesh
      consuming the device-resident trajectory queue, learner→actor D2D
      param broadcast, transfer guard ARMED over post-warmup actor windows.

    Reports env_steps/s + learner updates/s + actor_idle_frac +
    queue_depth_frac + staleness, and GATES the ISSUE 12 acceptance:
    every actor executable holds ``cache_size() == 1`` across the
    ``BENCH_SEBULBA_UPDATES`` (default 50) steady windows, and the
    sebulba run beats the adapter-path baseline on env-steps/s.
    """
    # CPU hosts need fake devices for a real device split — must land in
    # XLA_FLAGS before the backend initializes (no-op if already forced)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.sebulba.ppo import run_sebulba

    n_devices = len(jax.devices())
    n_actors = int(os.environ.get("BENCH_SEBULBA_ACTORS", max(1, n_devices // 2)))
    n_envs = int(os.environ.get("BENCH_SEBULBA_ENVS", 16))
    rollout_steps = int(os.environ.get("BENCH_SEBULBA_T", 16))
    updates = int(os.environ.get("BENCH_SEBULBA_UPDATES", 50))
    baseline_updates = int(os.environ.get("BENCH_SEBULBA_BASELINE_UPDATES", 8))

    common = [
        "exp=ppo_decoupled",
        "env=jax_cartpole",
        f"env.num_envs={n_envs}",
        "env.capture_video=False",
        "fabric.accelerator=auto",
        f"fabric.devices={n_devices}",
        f"algo.rollout_steps={rollout_steps}",
        f"algo.per_rank_batch_size={n_envs * rollout_steps}",
        "algo.update_epochs=1",
        "algo.cnn_keys.encoder=[]",
        "algo.mlp_keys.encoder=[state]",
        "algo.max_recompiles=1",
        "algo.run_test=False",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "buffer.memmap=False",
        "metric.log_level=0",
        "print_config=False",
    ]

    # ---- adapter-path decoupled baseline (pipelined topology) -------------
    from sheeprl_tpu.algos.ppo.ppo_decoupled import main as ppo_decoupled_main

    base_steps = n_envs * rollout_steps * baseline_updates
    cfg = compose(common + [
        f"algo.total_steps={base_steps}",
        "log_dir=/tmp/bench_sebulba_baseline",
    ])
    fabric = build_fabric(cfg)
    t0 = time.perf_counter()
    ppo_decoupled_main(fabric, cfg)
    baseline_wall = time.perf_counter() - t0
    baseline_rate = base_steps / baseline_wall

    # ---- sebulba device split ---------------------------------------------
    seb_steps = n_envs * rollout_steps * updates
    cfg = compose(common + [
        "topology=sebulba",
        f"topology.actor_devices={n_actors}",
        f"algo.total_steps={seb_steps}",
        "buffer.transfer_guard=True",  # actor steady windows run guarded
        "log_dir=/tmp/bench_sebulba_run",
    ])
    fabric = build_fabric(cfg)
    stats = run_sebulba(fabric, cfg)

    cache_ok = all(
        all(size == 1 for size in sizes.values()) for sizes in stats["actor_cache_sizes"]
    )
    beats = stats["env_steps_per_s"] > baseline_rate
    dev = jax.devices()[0]
    return {
        "metric": (
            f"sebulba_env_steps_per_s (ppo_decoupled jax-cartpole x{n_envs}, "
            f"{n_actors} actor + {max(n_devices - n_actors, 1)} learner devices, "
            f"{updates} windows, {dev.platform})"
        ),
        "value": round(stats["env_steps_per_s"], 1),
        "unit": "env_steps/s",
        # the acceptance comparison: sebulba jax-env actors vs the
        # adapter-path pipelined decoupled baseline on this host
        "vs_baseline": round(stats["env_steps_per_s"] / baseline_rate, 2),
        "env_steps_per_s": round(stats["env_steps_per_s"], 1),
        "env_steps_per_s_adapter_baseline": round(baseline_rate, 1),
        "updates_per_s": round(stats["updates_per_s"], 3),
        "actor_idle_frac": round(stats["actor_idle_frac"], 4),
        "queue_depth_frac": round(stats["queue_depth_frac"], 4),
        "param_staleness_max": stats["param_staleness_max"],
        "traj_staleness_max": stats["traj_staleness_max"],
        "traj_staleness_avg": round(stats["traj_staleness_avg"], 3),
        "actor_cache_sizes": stats["actor_cache_sizes"],
        "steady_windows": updates,
        "actor_devices": n_actors,
        "learner_devices": n_devices - n_actors if n_devices > 1 else 1,
        "worker_restarts": stats["worker_restarts"],
        "torn_rejected": stats["torn_rejected"],
        # step-phase breakdown of the learner window (telemetry/spans.py):
        # queue.wait vs rollout vs update.dispatch vs param.broadcast
        # fractions — the tuning signal for traj_queue_slots/max_staleness
        "phase_breakdown": stats["phase_breakdown"],
        "phase_frac_sum": _phase_frac_sum(stats["phase_breakdown"]),
        # ISSUE 12 acceptance gates: compile-once actor inference across the
        # steady windows under the armed guard, and beating the adapter path
        "cache_size_one": cache_ok,
        "beats_adapter_baseline": beats,
        "gate_failed": not (cache_ok and beats),
    }


def bench_dcn() -> dict:
    """Cross-host (fake-DCN) pod transport benchmark (``--mode dcn``,
    ISSUE 19).

    Two measured phases over a REAL 2-process pod (``SHEEPRL_FAKE_DCN``
    learner + actor cells; segments and params cross the process boundary
    over the learner front's HTTP transport):

    * **throughput** — a fresh ppo_decoupled pod run to
      ``BENCH_DCN_STEPS``; rank 0's ``POD_STATS_JSON`` line yields the
      DCN counters: param-broadcast publishes/bytes, segment intake
      rate/bytes, push retries/waits, staleness ledgers;
    * **restart** — the same pod relaunched with a raised step budget and
      ``checkpoint.resume_from=auto`` (exactly what the pod supervisor
      appends after a preemption); the bench times spawn → first NEW
      committed snapshot: the end-to-end pod recovery latency (init +
      coordinated resume + warmup + first window + all-rank commit).

    GATES the never-drop contract across the DCN: every segment the actor
    cell ever enqueued was accepted by the learner front
    (``queue_total_put == segments_accepted``) with zero rejects in a
    clean run.
    """
    import glob as _glob
    import shutil
    import subprocess
    import sys as _sys

    steps = int(os.environ.get("BENCH_DCN_STEPS", 64))
    hosts = max(2, int(os.environ.get("BENCH_DCN_HOSTS", 2)))
    log_dir = "/tmp/bench_dcn"
    shutil.rmtree(log_dir, ignore_errors=True)

    common = [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.max_episode_steps=16",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "topology=pod",
        "topology.env_workers=2",
        "fabric.devices=auto",
        "fabric.accelerator=cpu",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        "checkpoint.every=16",
        "checkpoint.save_last=False",
        "checkpoint.commit_timeout_s=30",
        "buffer.memmap=False",
        "metric.log_level=1",
        "metric.log_every=1",
        f"log_dir={log_dir}",
        "print_config=False",
    ]

    def run_pod(extra: list, timeout_s: float = 420.0) -> tuple:
        env = dict(os.environ)
        env.update({"SHEEPRL_FAKE_DCN": str(hosts), "JAX_PLATFORMS": "cpu"})
        env.pop("BENCH_CHILD", None)
        proc = subprocess.Popen(
            [_sys.executable, "-m", "sheeprl_tpu", *common, *extra],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        t0 = time.perf_counter()
        first_commit_s = None
        existing = set(_glob.glob(os.path.join(log_dir, "**", "COMMIT"), recursive=True))
        stats = None
        # line-by-line so the commit watch has real-time resolution
        deadline = time.monotonic() + timeout_s
        for line in proc.stdout:  # type: ignore[union-attr]
            if "POD_STATS_JSON=" in line:
                stats = json.loads(line.split("POD_STATS_JSON=", 1)[1])
            if first_commit_s is None:
                fresh = (
                    set(_glob.glob(os.path.join(log_dir, "**", "COMMIT"), recursive=True))
                    - existing
                )
                if fresh:
                    first_commit_s = time.perf_counter() - t0
            if time.monotonic() > deadline:
                proc.kill()
                break
        rc = proc.wait(timeout=60)
        if rc != 0 or stats is None:
            raise RuntimeError(f"bench_dcn pod run failed (rc={rc}, stats={stats is not None})")
        return stats, first_commit_s, time.perf_counter() - t0

    # ---- phase 1: clean-run DCN throughput --------------------------------
    stats, _, wall = run_pod([f"algo.total_steps={steps}"])
    dcn = stats.get("dcn", {})
    drop = stats.get("zero_drop", {})
    accepted = int(drop.get("segments_accepted", 0))
    rejected = int(drop.get("segments_rejected", 0))
    total_put = int(drop.get("queue_total_put", -1))
    zero_drop_ok = accepted == total_put and rejected == 0 and accepted > 0
    seg_bytes = float(dcn.get("Dcn/segment_bytes", 0.0))
    bc_bytes = float(dcn.get("Dcn/broadcast_bytes", 0.0))
    bc_pubs = max(int(dcn.get("Dcn/broadcast_publishes", 0)), 1)

    # ---- phase 2: restart-to-first-update (the preemption recovery path) --
    _, first_commit_s, _ = run_pod(
        [f"algo.total_steps={steps + 32}", "checkpoint.resume_from=auto"]
    )

    return {
        "metric": (
            f"dcn_segments_per_s (ppo_decoupled pod, {hosts} fake hosts, "
            f"{steps} steps, cpu)"
        ),
        "value": round(accepted / wall, 2),
        "unit": "segments/s",
        "env_steps_per_s": round(stats.get("env_steps_per_s", 0.0), 2),
        "updates_per_s": round(stats.get("updates_per_s", 0.0), 3),
        "traj_mib_per_s": round(seg_bytes / wall / 2**20, 4),
        "broadcast_publishes": int(dcn.get("Dcn/broadcast_publishes", 0)),
        "broadcast_kib_per_publish": round(bc_bytes / bc_pubs / 1024, 1),
        "push_retries": int(dcn.get("rank1/Dcn/push_retries", 0)),
        "backpressured": int(dcn.get("Dcn/backpressured", 0)),
        "param_staleness_max": stats.get("param_staleness_max", 0),
        "traj_staleness_max": stats.get("traj_staleness_max", 0),
        "torn_rejected": stats.get("torn_rejected", 0),
        # pod recovery latency: relaunch with resume_from=auto (what the
        # pod supervisor does after a preemption) -> first NEW all-rank
        # commit.  None means the resumed run never committed in time.
        "restart_to_first_commit_s": (
            round(first_commit_s, 2) if first_commit_s is not None else None
        ),
        # the never-drop contract, measured across a real process boundary
        "zero_drop": {
            "queue_total_put": total_put,
            "segments_accepted": accepted,
            "segments_rejected": rejected,
        },
        "zero_drop_ok": zero_drop_ok,
        "gate_failed": not zero_drop_ok or first_commit_s is None,
    }


def bench_pipeline() -> dict:
    """MPMD pipeline-parallel world-model update bench (``--mode pipeline``,
    ISSUE 16).

    Two measured arms of the SAME DreamerV3 train phase
    (``_build_dv3_train_phase`` — the benchmarked program IS the training
    program):

    * **GSPMD baseline** — data-parallel mesh over every device, the
      ``pipeline`` group off (the monolithic pre-pipeline program);
    * **pipelined** — a ``pipeline`` mesh axis + ``pipeline=2stage``: the
      world-model update runs as the in-trace 1F1B microbatch schedule
      (parallel/pipeline.py, docs/pipeline.md) inside the same ONE jitted
      dispatch.

    Reports updates/s for both arms, the schedule's bubble fraction, and a
    per-stage phase breakdown — ``pipeline.stage.<name>.fwd/.bwd`` spans
    timed over standalone ``compile_stage_pair`` programs built from the
    same stage functions the fused phase pipelines (``make_wm_stages``).
    GATES the ISSUE 16 acceptance: ``steady_compiles == 0`` across both
    armed steady windows, ``cache_size() == 1`` for both phase
    executables, and the span fractions summing to ~1.0.  The speedup
    ratio is reported but NOT gated: fake CPU devices share host cores, so
    the A/B only orders truthfully on real chips (BENCH_TPU.md).
    """
    # CPU hosts need fake devices for a real pipeline axis — must land in
    # XLA_FLAGS before the backend initializes (no-op if already forced)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.utils.profiler import COMPILE_MONITOR
    from sheeprl_tpu.utils.utils import device_sync

    n_devices = len(jax.devices())
    size = os.environ.get("BENCH_PIPE_SIZE", "XS")
    L = int(os.environ.get("BENCH_PIPE_L", 8))
    B = int(os.environ.get("BENCH_PIPE_B", 8))
    U = int(os.environ.get("BENCH_PIPE_U", 1))
    iters = int(os.environ.get("BENCH_PIPE_ITERS", 6))
    stage_iters = int(os.environ.get("BENCH_PIPE_STAGE_ITERS", 5))
    # pipelined-arm mesh: 4-deep pipeline axis when the device count allows,
    # 2-deep otherwise (B must stay divisible by BOTH data axes below)
    if os.environ.get("BENCH_PIPE_MESH"):
        pipe_mesh = os.environ["BENCH_PIPE_MESH"]
    elif n_devices % 4 == 0 and n_devices >= 8:
        pipe_mesh = f"{{data: {n_devices // 4}, pipeline: 4}}"
    else:
        pipe_mesh = f"{{data: {max(1, n_devices // 2)}, pipeline: 2}}"

    common = [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=discrete_dummy",
        f"algo=dreamer_v3_{size}",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[]",
        f"algo.per_rank_batch_size={B}",
        f"algo.per_rank_sequence_length={L}",
        "algo.max_recompiles=1",
        "fabric.accelerator=auto",
        f"fabric.devices={n_devices}",
        "print_config=False",
    ]

    rng = np.random.default_rng(0)
    block_np = {
        "rgb": rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8),
        "actions": rng.integers(0, 2, (U, L, B, 4)).astype(np.float32),
        "rewards": rng.normal(size=(U, L, B)).astype(np.float32),
        "terminated": np.zeros((U, L, B), np.float32),
        "is_first": np.zeros((U, L, B), np.float32),
    }

    def _arm(extra):
        """One measured arm: build the phase, warm it, then time `iters`
        steady windows under the armed H2D transfer guard."""
        cfg = compose(common + extra)
        fabric = build_fabric(cfg)
        train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
        block = fabric.shard_batch(
            {k: jnp.asarray(v) for k, v in block_np.items()}, axis=2
        )
        key = jax.random.PRNGKey(0)
        t0 = time.perf_counter()
        params, opt_state, metrics = train_phase(params, opt_state, block, key, jnp.int32(0))
        device_sync((params, metrics))
        first_call_s = time.perf_counter() - t0
        # counters pre-staged OUTSIDE the guard (eager host ints are H2D)
        steps_dev = [jax.device_put(np.int32(i + 1)) for i in range(iters)]
        n0, _ = COMPILE_MONITOR.totals()
        t0 = time.perf_counter()
        with jax.transfer_guard_host_to_device("disallow"):
            for i in range(iters):
                params, opt_state, metrics = train_phase(
                    params, opt_state, block, key, steps_dev[i]
                )
        device_sync((params, metrics))
        wall = time.perf_counter() - t0
        n1, _ = COMPILE_MONITOR.totals()
        return {
            "updates_per_s": U * iters / wall,
            "first_call_s": first_call_s,
            "steady_compiles": n1 - n0,
            "cache_size": train_phase.cache_size(),
            "mesh_shape": {k: int(v) for k, v in fabric.mesh.shape.items()},
        }, cfg, fabric

    base, _, _ = _arm([f"fabric.mesh_shape={{data: {n_devices}}}"])
    pipe_arm, pipe_cfg, pipe_fabric = _arm(
        [f"fabric.mesh_shape={pipe_mesh}", "pipeline=2stage"]
    )

    # ---- per-stage phase breakdown (standalone stage programs) ------------
    from gymnasium import spaces

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_wm_stages
    from sheeprl_tpu.parallel.pipeline import (
        compile_stage_pair, resolve_pipeline, split_microbatches,
    )
    from sheeprl_tpu.telemetry.spans import SPANS
    from sheeprl_tpu.utils.distribution import OneHotCategorical

    spec = resolve_pipeline(pipe_cfg)
    obs_space = spaces.Dict({"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, _, _, agent_params = build_agent(
        pipe_fabric, (4,), False, pipe_cfg, obs_space
    )
    wm_params = agent_params["world_model"]
    _, stage_fns, stage_names = make_wm_stages(pipe_cfg, world_model, ("rgb",), ())

    data = {k: jnp.asarray(v[0]) for k, v in block_np.items()}  # one (L, B, *) update
    noise = jax.vmap(
        lambda kk: OneHotCategorical.sample_noise(
            kk, (B, world_model.stochastic_size, world_model.discrete_size)
        )
    )(jax.random.split(jax.random.PRNGKey(1), L))
    consts = split_microbatches({"data": data, "noise": noise}, spec.microbatches, axis=1)
    const_mb = jax.tree.map(lambda a: a[0], consts)  # one microbatch slice

    programs = []
    carry = None
    for raw, nm in zip(stage_fns, stage_names):
        # the params ride as the differentiable operand so the stage
        # backward measures the REAL 1F1B cost (param grads); the carry and
        # microbatch const are baked in as program constants
        def _stage(p, x, _raw=raw, _carry=carry):
            return _raw(x, _carry, const_mb)

        fwd_c, bwd_c = compile_stage_pair(pipe_fabric, _stage, name=f"pipeline.stage.{nm}")
        out = fwd_c(wm_params, wm_params)  # warm fwd; also the next stage's carry
        px = jax.tree.map(lambda a: a.copy(), wm_params)
        dy = jax.tree.map(jnp.ones_like, out)
        bwd_c(wm_params, px, dy)  # warm bwd (compiles land outside the spans)
        programs.append((nm, fwd_c, bwd_c))
        carry = out

    SPANS.roll_window()
    for _ in range(stage_iters):
        for nm, fwd_c, bwd_c in programs:
            with SPANS.span(f"pipeline.stage.{nm}.fwd"):
                out = fwd_c(wm_params, wm_params)
                device_sync(out)
            # canonical rebinding: bwd DONATES the activation copy and the
            # cotangent — both are freshly created every iteration
            px = jax.tree.map(lambda a: a.copy(), wm_params)
            dy = jax.tree.map(jnp.ones_like, out)
            with SPANS.span(f"pipeline.stage.{nm}.bwd"):
                grads = bwd_c(wm_params, px, dy)
                device_sync(grads)
    breakdown = SPANS.breakdown()

    steady_compiles = base["steady_compiles"] + pipe_arm["steady_compiles"]
    cache_ok = base["cache_size"] == 1 and pipe_arm["cache_size"] == 1
    frac_sum = _phase_frac_sum(breakdown)
    frac_ok = abs(frac_sum - 1.0) < 0.02
    dev = jax.devices()[0]
    return {
        "metric": (
            f"dreamer_v3_{size}_pipelined_updates_per_s "
            f"(S={spec.stages} M={spec.microbatches} 1f1b, mesh {pipe_mesh}, "
            f"B={B} L={L} U={U}, {dev.platform})"
        ),
        "value": round(pipe_arm["updates_per_s"], 3),
        "unit": "updates/s",
        # reported, not gated: fake CPU devices share host cores
        "vs_baseline": round(pipe_arm["updates_per_s"] / base["updates_per_s"], 3),
        "updates_per_s_pipelined": round(pipe_arm["updates_per_s"], 3),
        "updates_per_s_gspmd_baseline": round(base["updates_per_s"], 3),
        "first_call_s_pipelined": round(pipe_arm["first_call_s"], 3),
        "first_call_s_gspmd_baseline": round(base["first_call_s"], 3),
        "pipeline": {
            "stages": spec.stages,
            "microbatches": spec.microbatches,
            "schedule": spec.schedule,
            "stage_names": list(stage_names),
        },
        # the schedule's idle fraction (S-1)/(M+S-1) — docs/pipeline.md
        "bubble_frac": round(spec.bubble_frac, 6),
        "mesh_shape_pipelined": pipe_arm["mesh_shape"],
        "mesh_shape_baseline": base["mesh_shape"],
        "steady_windows": iters,
        # per-stage fwd/bwd wall fractions (pipeline.stage.* spans): the
        # stage-balance tuning signal behind pipeline.stages grouping
        "phase_breakdown": breakdown,
        "phase_frac_sum": frac_sum,
        # ISSUE 16 acceptance gates: compile-once across both armed steady
        # windows + the span fractions accounting for the whole window
        "steady_compiles": steady_compiles,
        "cache_size_one": cache_ok,
        "gate_failed": not (steady_compiles == 0 and cache_ok and frac_ok),
    }


def bench_fault_overhead() -> dict:
    """Zero-overhead gate for the fault-injection layer (docs/resilience.md).

    The engine's contract is that an EMPTY fault plan compiles to a no-op
    (the process-global plan is ``None`` and every instrumented site is a
    single pointer test).  This bench holds it to the number the ISSUE
    names: steady-state DreamerV3 updates/s with fault injection installed-
    but-empty must be within ``BENCH_FAULT_TOL`` (default 2%) of the
    uninstrumented baseline — measured as INTERLEAVED A/B windows over the
    same compiled executable so host noise hits both arms alike — and the
    empty-plan run must emit zero ``Resilience/*`` metrics.

    ``gate_failed: true`` in the payload (and a nonzero exit) on violation.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.resilience.faults import FaultPlan, clear_plan, fault_point, install_plan
    from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR
    from sheeprl_tpu.utils.utils import device_sync

    size = os.environ.get("BENCH_SIZE", "XS")
    L = int(os.environ.get("BENCH_L", 8))
    B = int(os.environ.get("BENCH_B", 4))
    U = int(os.environ.get("BENCH_U", 2))
    samples = int(os.environ.get("BENCH_FAULT_SAMPLES", 12))
    tol = float(os.environ.get("BENCH_FAULT_TOL", 0.02))

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            f"algo=dreamer_v3_{size}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            f"algo.per_rank_batch_size={B}",
            f"algo.per_rank_sequence_length={L}",
        ]
    )
    fabric = build_fabric(cfg)
    rng = np.random.default_rng(0)
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
    block = fabric.shard_batch(block, axis=2)
    key = jax.random.PRNGKey(0)

    # warm up once; both arms reuse this one executable
    params, opt_state, metrics = train_phase(params, opt_state, block, key, jnp.int32(0))
    device_sync((params, metrics))

    RESILIENCE_MONITOR.reset()

    step = 0

    def one_dispatch(hooked: bool):
        nonlocal params, opt_state, step
        t0 = time.perf_counter()
        if hooked:
            # the instrumented arm must HIT a real site or the gate is
            # vacuous: real train iterations poll fabric.copy_to (player
            # sync) once per iteration, so pay the same hook here.  The
            # baseline arm deliberately does NOT call it — a regression of
            # the disabled fast path must show up as a DIFFERENCE, not
            # cancel out across both arms.
            fault_point("fabric.copy_to")
        params, opt_state, metrics = train_phase(
            params, opt_state, block, key, jnp.int32(step)
        )
        device_sync((params, metrics))
        step += 1
        return time.perf_counter() - t0

    one_dispatch(False)  # discard one warm-in dispatch (caches, allocator)

    # Estimator chosen for a noisy shared host: a dispatch only ever gets
    # SLOWED by contention (noise is strictly one-sided), so each arm's
    # MIN-of-N dispatch time is a tight estimate of its attainable latency;
    # arms alternate per dispatch so drift cannot systematically favor one.
    baseline, empty_plan = [], []
    for s in range(2 * samples):
        if s % 2 == 0:
            clear_plan()  # fault injection entirely absent, no hook called
            baseline.append(one_dispatch(False))
        else:
            # the user-facing "enabled with an empty plan" spelling —
            # install_plan MUST fold it to None (the zero-overhead contract)
            install_plan(FaultPlan.from_specs([]))
            empty_plan.append(one_dispatch(True))
    clear_plan()

    base = U / min(baseline)  # attainable updates/s, no fault layer
    empty = U / min(empty_plan)  # …with an installed-but-empty plan
    # directional: only a SLOWDOWN of the empty-plan arm is a regression —
    # the arms run near-identical code, so "empty came out faster" is noise
    # and must not fail CI
    overhead = max(0.0, (base - empty) / base)
    leaked = RESILIENCE_MONITOR.metrics()  # must be {} — nothing recorded
    gate_failed = overhead >= tol or bool(leaked)
    return {
        "metric": (
            f"fault_injection_empty_plan_overhead "
            f"(dreamer_v3_{size} B={B} L={L} U={U}, {samples}x interleaved A/B, min-estimator)"
        ),
        "value": round(overhead * 100, 3),
        "unit": "%",
        "vs_baseline": None,
        "steady_updates_per_s_no_plan": round(base, 4),
        "steady_updates_per_s_empty_plan": round(empty, 4),
        "tolerance_pct": tol * 100,
        "resilience_metrics_emitted": leaked,
        "gate_failed": gate_failed,
    }


def bench_telemetry_overhead() -> dict:
    """Zero-overhead gate for the telemetry subsystem (docs/telemetry.md).

    Default-on telemetry (span push/pop per phase, the recorder's span-edge
    events, the tracer tick) must cost <``BENCH_TELEMETRY_TOL`` (default
    2%) of steady-state DreamerV3 updates/s — measured exactly like the
    fault-injection gate: INTERLEAVED A/B windows over the same compiled
    executable, min-of-N per arm (host noise is one-sided), directional
    (only a slowdown of the instrumented arm can fail).  The instrumented
    arm pays the real per-update span load: a top-level rollout span, a
    top-level update.dispatch span (which also ticks the trace scheduler)
    and a nested queue-wait span.

    ``gate_failed: true`` in the payload (and a nonzero exit) on violation.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.telemetry.spans import SPANS, span
    from sheeprl_tpu.utils.utils import device_sync

    size = os.environ.get("BENCH_SIZE", "XS")
    L = int(os.environ.get("BENCH_L", 8))
    B = int(os.environ.get("BENCH_B", 4))
    U = int(os.environ.get("BENCH_U", 2))
    samples = int(os.environ.get("BENCH_TELEMETRY_SAMPLES", 12))
    tol = float(os.environ.get("BENCH_TELEMETRY_TOL", 0.02))

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            f"algo=dreamer_v3_{size}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            f"algo.per_rank_batch_size={B}",
            f"algo.per_rank_sequence_length={L}",
        ]
    )
    fabric = build_fabric(cfg)
    rng = np.random.default_rng(0)
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
    block = fabric.shard_batch(block, axis=2)
    key = jax.random.PRNGKey(0)

    # warm up once; both arms reuse this one executable
    params, opt_state, metrics = train_phase(params, opt_state, block, key, jnp.int32(0))
    device_sync((params, metrics))

    step = 0

    def one_dispatch(instrumented: bool):
        nonlocal params, opt_state, step
        t0 = time.perf_counter()
        if instrumented:
            # the real per-update span load of an instrumented train loop:
            # rollout + nested queue wait, then the train dispatch (whose
            # top-level span also ticks the trace scheduler)
            with span("rollout"):
                with span("queue.wait"):
                    pass
            with span("update.dispatch"):
                params, opt_state, metrics = train_phase(
                    params, opt_state, block, key, jnp.int32(step)
                )
        else:
            params, opt_state, metrics = train_phase(
                params, opt_state, block, key, jnp.int32(step)
            )
        device_sync((params, metrics))
        step += 1
        return time.perf_counter() - t0

    one_dispatch(False)  # discard one warm-in dispatch (caches, allocator)

    # interleaved A/B, min-of-N estimator — the fault_overhead pattern:
    # noise on a shared host only ever SLOWS a dispatch, so each arm's
    # minimum is a tight attainable-latency estimate, and alternating
    # arms keeps drift from systematically favoring one
    baseline, instrumented = [], []
    for s in range(2 * samples):
        if s % 2 == 0:
            SPANS.enabled = False
            baseline.append(one_dispatch(False))
        else:
            SPANS.enabled = True
            instrumented.append(one_dispatch(True))
    SPANS.enabled = True
    phase_breakdown = SPANS.breakdown()

    base = U / min(baseline)
    instr = U / min(instrumented)
    # directional: only a SLOWDOWN of the instrumented arm is a regression
    overhead = max(0.0, (base - instr) / base)
    gate_failed = overhead >= tol
    return {
        "metric": (
            f"telemetry_span_overhead "
            f"(dreamer_v3_{size} B={B} L={L} U={U}, {samples}x interleaved A/B, min-estimator)"
        ),
        "value": round(overhead * 100, 3),
        "unit": "%",
        "vs_baseline": None,
        "steady_updates_per_s_disabled": round(base, 4),
        "steady_updates_per_s_instrumented": round(instr, 4),
        "tolerance_pct": tol * 100,
        "phase_breakdown": phase_breakdown,
        "phase_frac_sum": _phase_frac_sum(phase_breakdown),
        "gate_failed": gate_failed,
    }


def bench_health_overhead() -> dict:
    """Cost gate for the default-on training-health sentinels
    (resilience/health.py, docs/supervisor.md).

    The non-finite guard compiles INTO the update dispatch: after the
    train phase's own math it reduces ``isfinite`` over the loss and the
    fresh params and selects old-vs-new — extra device work every window,
    so unlike the fault/telemetry gates the two arms here are genuinely
    DIFFERENT executables: A is the health-guarded DreamerV3 train phase
    (``health.enabled=true``, the default), B is the same phase with the
    sentinel compiled out.  Both are AOT-warmed, then timed as interleaved
    A/B windows with the min-of-N estimator (host noise is one-sided);
    the guarded arm must stay within ``BENCH_HEALTH_TOL`` (default 2%).

    ``gate_failed: true`` in the payload (and a nonzero exit) on violation.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.resilience.health import HealthSentinel
    from sheeprl_tpu.utils.utils import device_sync

    size = os.environ.get("BENCH_SIZE", "XS")
    L = int(os.environ.get("BENCH_L", 8))
    B = int(os.environ.get("BENCH_B", 4))
    U = int(os.environ.get("BENCH_U", 2))
    samples = int(os.environ.get("BENCH_HEALTH_SAMPLES", 12))
    tol = float(os.environ.get("BENCH_HEALTH_TOL", 0.02))

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            f"algo=dreamer_v3_{size}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            f"algo.per_rank_batch_size={B}",
            f"algo.per_rank_sequence_length={L}",
        ]
    )
    fabric = build_fabric(cfg)
    rng = np.random.default_rng(0)
    block = {
        "rgb": jnp.asarray(rng.integers(0, 255, (U, L, B, 64, 64, 3)).astype(np.uint8)),
        "actions": jnp.asarray(rng.integers(0, 2, (U, L, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(U, L, B)).astype(np.float32)),
        "terminated": jnp.zeros((U, L, B), jnp.float32),
        "is_first": jnp.zeros((U, L, B), jnp.float32),
    }
    train_phase, params, opt_state = _build_dv3_train_phase(fabric, cfg)
    block = fabric.shard_batch(block, axis=2)
    key = jax.random.PRNGKey(0)

    sentinel = HealthSentinel(cfg.get("health") or {}, fabric)
    guarded = fabric.compile(
        sentinel.wrap(train_phase),
        name="bench.health_guarded",
        donate_argnums=(0, 1, 2),
    )

    # per-arm state chains (the arms are different executables and both
    # donate their params/opt-state — each must consume only its own)
    p_a = jax.tree.map(jnp.copy, params)
    o_a = jax.tree.map(jnp.copy, opt_state)
    p_b, o_b = params, opt_state
    h = sentinel.init_state()

    # warm both executables before timing anything
    h, p_a, o_a, m = guarded(h, p_a, o_a, block, key, jnp.int32(0))
    device_sync((p_a, m))
    p_b, o_b, m = train_phase(p_b, o_b, block, key, jnp.int32(0))
    device_sync((p_b, m))

    step = 0

    def one_dispatch(guarded_arm: bool):
        nonlocal p_a, o_a, p_b, o_b, h, step
        t0 = time.perf_counter()
        if guarded_arm:
            h, p_a, o_a, m = guarded(h, p_a, o_a, block, key, jnp.int32(step))
            device_sync((p_a, m))
        else:
            p_b, o_b, m = train_phase(p_b, o_b, block, key, jnp.int32(step))
            device_sync((p_b, m))
        step += 1
        return time.perf_counter() - t0

    one_dispatch(False)  # discard one warm-in dispatch (caches, allocator)
    one_dispatch(True)

    # interleaved A/B, min-of-N estimator (the fault_overhead pattern)
    baseline, instrumented = [], []
    for s in range(2 * samples):
        if s % 2 == 0:
            baseline.append(one_dispatch(False))
        else:
            instrumented.append(one_dispatch(True))

    base = U / min(baseline)
    instr = U / min(instrumented)
    # directional: only a SLOWDOWN of the guarded arm is a regression
    overhead = max(0.0, (base - instr) / base)
    gate_failed = overhead >= tol or guarded.cache_size() != 1
    return {
        "metric": (
            f"health_sentinel_overhead "
            f"(dreamer_v3_{size} B={B} L={L} U={U}, {samples}x interleaved A/B, min-estimator)"
        ),
        "value": round(overhead * 100, 3),
        "unit": "%",
        "vs_baseline": None,
        "steady_updates_per_s_unguarded": round(base, 4),
        "steady_updates_per_s_guarded": round(instr, 4),
        "tolerance_pct": tol * 100,
        "guarded_cache_size": guarded.cache_size(),
        "gate_failed": gate_failed,
    }


def bench_lint() -> dict:
    """graftlint wall-time gate (``--mode lint``, ISSUE 15).

    Times the whole-package static-analysis run (the run_ci stage 14 /
    tier-1 workload) and gates it like any other perf surface: findings
    mean the repo broke the zero-unsuppressed invariant, stale baseline
    entries mean a fixed finding kept its ledger entry, and a >60 s wall
    means the analyzer outgrew its CI budget.  Pure host work — no jax
    dispatch, no accelerator involvement."""
    from sheeprl_tpu.analysis import Baseline, DEFAULT_BASELINE, run_analysis

    t0 = time.perf_counter()
    report = run_analysis(baseline=Baseline.load(DEFAULT_BASELINE))
    wall = time.perf_counter() - t0

    budget_s = float(os.environ.get("BENCH_LINT_BUDGET_S", 60.0))
    gate_failed = bool(
        report.findings or report.stale_baseline or wall > budget_s
    )
    return {
        "metric": f"graftlint_wall (whole sheeprl_tpu/, {report.files_analyzed} files)",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": None,
        "files_analyzed": report.files_analyzed,
        "unsuppressed_findings": len(report.findings),
        "findings_by_rule": report.counts(),
        "baselined": len(report.baselined),
        "comment_suppressed": len(report.suppressed),
        "stale_baseline_entries": len(report.stale_baseline),
        "budget_s": budget_s,
        "gate_failed": gate_failed,
    }


def _run_bench() -> dict:
    target = os.environ.get("BENCH_TARGET", "dreamer_v3")
    if target == "lint":
        return bench_lint()
    if target == "serve":
        return bench_serve()
    if target == "serve_fleet":
        return bench_serve_fleet()
    if target == "replay":
        return bench_device_replay()
    if target == "fault_overhead":
        return bench_fault_overhead()
    if target == "telemetry_overhead":
        return bench_telemetry_overhead()
    if target == "health_overhead":
        return bench_health_overhead()
    if target == "env":
        return bench_env()
    if target == "population":
        return bench_population()
    if target == "sebulba":
        return bench_sebulba()
    if target == "dcn":
        return bench_dcn()
    if target == "pipeline":
        return bench_pipeline()
    if target in BASELINE_CPU_WALL_CLOCK_S:
        return bench_cpu_wall_clock(target)
    return bench_dreamer_v3()


def _watchdog_main() -> None:
    """Run the accelerator bench in a CHILD process with a hard timeout.

    Round-1 failure mode (BENCH_r01: rc=124): a half-wedged TPU tunnel can
    pass a liveness probe (even a small dispatch) and then hang on the first
    big compile — the only robust guard is a watchdog around the WHOLE bench
    body.  On timeout/crash the parent re-runs itself on CPU and labels the
    fallback in the metric name.
    """
    import subprocess
    import sys

    from sheeprl_tpu.utils.utils import accelerator_alive

    def run_child(env: dict, timeout_s: int):
        """Run the bench body in a child; return (parsed JSON dict | None).
        Surfaces the child's stderr tail on failure (stderr only — stdout
        stays ONE JSON line for the driver)."""
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                timeout=timeout_s,
                capture_output=True,
                text=True,
                env=env,
            )
        except subprocess.TimeoutExpired:
            print(f"[bench] child timed out after {timeout_s}s", file=sys.stderr)
            return None
        for line in reversed((child.stdout or "").strip().splitlines()):
            try:
                return json.loads(line)
            except (ValueError, TypeError):
                continue
        # no JSON produced: a genuine bench bug, not an infra outage — show it
        tail = (child.stderr or "").strip().splitlines()[-15:]
        print("[bench] child produced no JSON; stderr tail:", file=sys.stderr)
        for line in tail:
            print(f"[bench] {line}", file=sys.stderr)
        return None

    def emit(result) -> None:
        if result is None:
            result = {"metric": "bench_failed", "value": 0, "unit": "", "vs_baseline": None}
        print(json.dumps(result))

    # default timeout must comfortably cover the workload: the dreamer _wall
    # baselines alone are 1589-2207s on the reference's 4-CPU host
    target = os.environ.get("BENCH_TARGET")
    default_timeout = 1200
    if target in BASELINE_CPU_WALL_CLOCK_S:
        default_timeout = max(1200, int(4 * BASELINE_CPU_WALL_CLOCK_S[target]))
    timeout_s = int(os.environ.get("BENCH_TIMEOUT", default_timeout))
    env = {**os.environ, "BENCH_CHILD": "1"}
    if os.environ.get("BENCH_TARGET") in BASELINE_CPU_WALL_CLOCK_S:
        if os.environ.get("BENCH_ON_ACCEL"):
            # the reference's benchmark workload end-to-end on the chip:
            # the hardware axis IS the comparison (labeled in the metric).
            # An inherited JAX_PLATFORMS=cpu would silently benchmark the
            # CPU under an on-accelerator label — strip it.
            env.pop("JAX_PLATFORMS", None)
            if accelerator_alive():
                result = run_child(env, timeout_s)
                if result is not None:
                    emit(result)
                    return
            emit(None)
            return
        # CPU wall-clock benchmarks are CPU by definition otherwise (the
        # baseline is the reference's 4-CPU number) — don't touch the tunnel.
        env["JAX_PLATFORMS"] = "cpu"
        emit(run_child(env, timeout_s))
        return
    if accelerator_alive():
        result = run_child(env, timeout_s)
        if result is not None:
            emit(result)
            return
    # accelerator dead or bench hung/crashed: CPU fallback, honestly labeled.
    # Default to a small workload there (S-sized pixel batches take >30min on
    # a 1-core host — the fallback must produce a number, not a new hang);
    # explicit BENCH_* overrides still win, so the fallback keeps its own
    # hard timeout too.
    env["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("BENCH_TARGET", "dreamer_v3") == "dreamer_v3":
        env.setdefault("BENCH_SIZE", "XS")
        env.setdefault("BENCH_L", "8")
        env.setdefault("BENCH_B", "4")
        env.setdefault("BENCH_U", "2")
    result = run_child(env, timeout_s)
    if result is not None:
        result["metric"] += (
            " [accelerator unreachable: CPU fallback; real-chip captures in BENCH_TPU.md]"
        )
    emit(result)


if __name__ == "__main__":
    import sys

    # `--mode <target>` CLI alias for BENCH_TARGET (e.g. `bench.py --mode
    # serve`); the env var form keeps working and is what the watchdog's
    # child re-exec inherits
    if "--mode" in sys.argv:
        idx = sys.argv.index("--mode")
        if idx + 1 >= len(sys.argv):
            raise SystemExit("--mode requires a target (serve, dreamer_v3, ppo, ...)")
        os.environ["BENCH_TARGET"] = sys.argv[idx + 1]

    from sheeprl_tpu.utils.utils import force_cpu_backend

    if (
        os.environ.get("BENCH_CHILD") == "1"
        or os.environ.get("JAX_PLATFORMS") == "cpu"
        # graftlint is pure host AST work: never probe the accelerator for it
        or os.environ.get("BENCH_TARGET") == "lint"
    ):
        # child (or explicit CPU request): run the bench body directly
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            # the TPU plugin overrides the env var; jax.config wins
            force_cpu_backend()
        result = _run_bench()
        # every mode's payload is self-describing: mode, git SHA and
        # host/device inventory ride along (BENCH_*.json archaeology must
        # not need the shell history that produced the file)
        result.update(_bench_stamp(os.environ.get("BENCH_TARGET", "dreamer_v3")))
        print(json.dumps(result))
        if result.get("gate_failed"):
            # the fault-overhead gate is an ASSERTION: empty-plan steady
            # state drifted beyond tolerance (or Resilience/* leaked)
            sys.exit(1)
    else:
        _watchdog_main()
