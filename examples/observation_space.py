"""Print the WRAPPED observation space an agent will actually see for any
env/algo config — the full make_env pipeline (Dict normalization, resize,
frame stack, reward/actions-as-obs) applied (reference parity:
examples/observation_space.py).

Usage:
    python examples/observation_space.py exp=dreamer_v3 env=dmc env.id=walker_walk
    python examples/observation_space.py exp=ppo env.id=CartPole-v1 env.frame_stack=4
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.utils.env import make_env


def main(argv) -> None:
    cfg = compose(list(argv) + ["env.capture_video=False"])
    env = make_env(cfg, cfg.seed, rank=0)()
    print(f"\nObservation space of `{cfg.env.id}` for `{cfg.algo.name}`:")
    print(env.observation_space)
    print(f"\nAction space: {env.action_space}")
    print(
        "\nKeys the agent encodes (algo.cnn_keys/mlp_keys): "
        f"cnn={list(cfg.algo.cnn_keys.encoder)} mlp={list(cfg.algo.mlp_keys.encoder)}"
    )
    env.close()


if __name__ == "__main__":
    main(sys.argv[1:])
