"""Template for a decoupled player/trainer topology — the TPU-native
equivalent of the reference's multi-process collectives demo
(reference parity: examples/architecture_template.py, which spawns
buffer/player/trainer processes over TorchCollective).

The reference needs three process groups and explicit object collectives.
The JAX runtime needs less machinery: each PROCESS owns its devices, the
trainer group is a sub-mesh, and host-object collectives (pickled pytrees
over the jax.distributed KV store) carry rollouts one way and weights the
other — see the production implementation in
sheeprl_tpu/algos/ppo/ppo_decoupled.py (dedicated topology) and
sheeprl_tpu/parallel/fabric.py (host collectives).

This template runs N processes on localhost CPU to show the skeleton:

    python examples/architecture_template.py --processes 3

process 0 = player (steps a fake env, ships rollouts), processes 1..N-1 =
trainers (consume rollouts, ship updated params back).  The lockstep
protocol (sync A: rollout -> trainers, sync B: weights -> player) is the
same one the real decoupled algorithms use.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def player(fabric, steps: int) -> None:
    import numpy as np

    params = fabric.broadcast_object(None, src=1)  # initial weights from trainer 1
    for step in range(steps):
        rollout = {"obs": np.random.default_rng(step).normal(size=(8, 4)).astype(np.float32)}
        # sync A: rollout -> every trainer
        fabric.broadcast_object(rollout, src=0)
        # sync B: refreshed weights <- trainer 1
        params = fabric.broadcast_object(None, src=1)
        print(f"[player] step {step}: got params v{params['version']}", flush=True)


def trainer(fabric, steps: int) -> None:
    import numpy as np

    params = {"w": np.zeros(4, np.float32), "version": 0}
    if fabric.global_rank == 1:
        fabric.broadcast_object(params, src=1)
    else:
        fabric.broadcast_object(None, src=1)
    for step in range(steps):
        rollout = fabric.broadcast_object(None, src=0)  # sync A
        params = {"w": params["w"] + rollout["obs"].mean(0), "version": params["version"] + 1}
        # (real trainers run the jitted update over the trainer sub-mesh here)
        fabric.broadcast_object(params if fabric.global_rank == 1 else None, src=1)  # sync B
        print(f"[trainer {fabric.global_rank}] step {step}: trained v{params['version']}", flush=True)


def worker(steps: int) -> None:
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric

    cfg = compose(
        [
            "env=dummy", "env.id=discrete_dummy", "algo=ppo",
            "algo.total_steps=1", "algo.per_rank_batch_size=1",
            "fabric.accelerator=cpu",
        ]
    )
    fabric = build_fabric(cfg)
    if fabric.global_rank == 0:
        player(fabric, steps)
    else:
        trainer(fabric, steps)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--processes", type=int, default=3)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--port", type=int, default=12939)
    args = p.parse_args()

    if os.environ.get("_ARCH_TEMPLATE_WORKER"):
        import jax

        jax.distributed.initialize(
            f"127.0.0.1:{args.port}",
            num_processes=args.processes,
            process_id=int(os.environ["_ARCH_TEMPLATE_WORKER"]) - 1,
        )
        worker(args.steps)
        return

    if args.processes < 2:
        p.error("--processes must be >= 2 (one player + at least one trainer)")
    procs = []
    for rank in range(args.processes):
        env = {
            **os.environ,
            "_ARCH_TEMPLATE_WORKER": str(rank + 1),
            "JAX_PLATFORMS": "cpu",
        }
        procs.append(subprocess.Popen([sys.executable, __file__] + sys.argv[1:], env=env))
    try:
        rcs = [pr.wait(timeout=300) for pr in procs]
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.kill()
    sys.exit(1 if any(rc != 0 for rc in rcs) else 0)


if __name__ == "__main__":
    main()
