"""Roll a DreamerV3 world model forward in IMAGINATION and dump the decoded
frames — the script equivalent of the reference's
notebooks/dreamer_v3_imagination.ipynb.

Given a checkpoint, the script encodes a few real environment frames into
the latent state, then imagines `--horizon` steps with the trained actor and
decodes each imagined latent back to pixels:

    python examples/dreamer_v3_imagination.py \
        checkpoint_path=<run>/checkpoint/ckpt_..._0.ckpt --horizon 30

Without a checkpoint it runs a self-contained demo on the pixel dummy env
with freshly initialized params (the rollout mechanics are identical; the
reconstructions are noise until trained):

    python examples/dreamer_v3_imagination.py --demo
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("overrides", nargs="*", help="checkpoint_path=... and config overrides")
    p.add_argument("--horizon", type=int, default=15)
    p.add_argument("--context", type=int, default=4, help="real frames to encode first")
    p.add_argument("--out", default="imagination.png")
    p.add_argument("--demo", action="store_true", help="run with fresh params on the dummy env")
    args = p.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import WorldModel, build_agent
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.utils.env import make_env

    ckpt = [o.split("=", 1)[1] for o in args.overrides if o.startswith("checkpoint_path=")]
    rest = [o for o in args.overrides if not o.startswith("checkpoint_path=")]
    state = None
    if ckpt:
        import yaml

        from sheeprl_tpu.config.compose import apply_cli_overrides
        from sheeprl_tpu.utils.checkpoint import load_checkpoint
        from sheeprl_tpu.utils.structured import dotdict

        run_cfg = Path(ckpt[0]).parent.parent / "config.yaml"
        with open(run_cfg) as f:
            cfg = dotdict(yaml.safe_load(f))
        apply_cli_overrides(cfg, rest)
        state = load_checkpoint(ckpt[0])
    elif args.demo:
        cfg = compose(
            [
                "exp=dreamer_v3", "env=dummy", "env.id=pixel_grid_dummy",
                "algo=dreamer_v3_XS", "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]", "fabric.accelerator=cpu",
                "env.capture_video=False", *rest,
            ]
        )
    else:
        p.error("pass checkpoint_path=... or --demo")

    cfg.fabric.devices = 1
    cfg.env.num_envs = 1
    fabric = build_fabric(cfg)
    env = make_env(cfg, cfg.seed, 0)()
    from sheeprl_tpu.algos.ppo.utils import spaces_to_dims

    actions_dim, is_continuous = spaces_to_dims(env.action_space)
    world_model, actor, critic, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, env.observation_space,
        state["agent"] if state else None,
    )
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    if not cnn_keys:
        sys.exit(
            "this example visualizes DECODED PIXELS; the checkpoint was trained "
            "without cnn keys (algo.cnn_keys.encoder is empty) — nothing to render"
        )
    cnn_key = cnn_keys[0]

    # --- encode a few real frames to settle the latent state ---------------
    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    rec = cfg.algo.world_model.recurrent_model.recurrent_state_size
    h = jnp.zeros((1, rec))
    z = jnp.zeros((1, world_model.stoch_flat))
    prev_a = jnp.zeros((1, int(sum(actions_dim))))
    wm_p = params["world_model"]

    from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs

    def frame_to_input(o):
        batched = {k: np.asarray(o[k])[None] for k in cnn_keys + mlp_keys}
        return prepare_obs(batched, cnn_keys, mlp_keys)

    real_frames = []
    for t in range(args.context):
        key, k_repr, k_act = jax.random.split(key, 3)
        embed = world_model.apply(wm_p, frame_to_input(obs), method=WorldModel.encode)
        is_first = jnp.full((1, 1), 1.0 if t == 0 else 0.0)
        h, z, _, _ = world_model.apply(
            wm_p, h, z, prev_a, embed, is_first, k_repr, method=WorldModel.dynamic
        )
        head = actor.apply(params["actor"], jnp.concatenate([z, h], -1))
        prev_a = actor.sample(head, k_act)
        real_frames.append(np.asarray(obs[cnn_key]))
        from sheeprl_tpu.algos.ppo.utils import actions_for_env

        obs, *_ = env.step(actions_for_env(np.asarray(prev_a), env.action_space))
    env.close()

    # --- imagine forward with the actor ------------------------------------
    imagined = []
    for _ in range(args.horizon):
        key, k_img, k_act = jax.random.split(key, 3)
        h, z = world_model.apply(wm_p, h, z, prev_a, k_img, method=WorldModel.imagination)
        latent = jnp.concatenate([z, h], -1)
        head = actor.apply(params["actor"], latent)
        prev_a = actor.sample(head, k_act)
        recon = world_model.apply(wm_p, latent, method=WorldModel.decode)[cnn_key]
        img = np.asarray(recon[0])
        n_ch = env.observation_space[cnn_key].shape[-1]  # channels per FRAME
        if img.ndim == 3 and img.shape[-1] > n_ch:  # merged frame-stack: keep last frame
            img = img[..., -n_ch:]
        imagined.append(np.clip((img + 0.5) * 255.0, 0, 255).astype(np.uint8))

    # --- dump a context|imagination film strip -----------------------------
    def to_rgb(f):
        f = f[-1] if f.ndim == 4 else f
        return f if f.shape[-1] == 3 else np.repeat(f[..., :1], 3, -1)

    strip = np.concatenate([to_rgb(f) for f in real_frames + imagined], axis=1)
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        plt.figure(figsize=(len(real_frames + imagined), 1.6))
        plt.imshow(strip)
        plt.axvline(real_frames[0].shape[1] * len(real_frames) - 0.5, color="red", lw=2)
        plt.axis("off")
        plt.title(f"{len(real_frames)} real frames | {args.horizon} imagined")
        plt.savefig(args.out, dpi=150, bbox_inches="tight")
        print(f"wrote {args.out}  (strip shape {strip.shape})")
    except ImportError:
        np.save(args.out + ".npy", strip)
        print(f"matplotlib unavailable; wrote raw strip to {args.out}.npy")


if __name__ == "__main__":
    main()
