"""Demonstrate the Ratio replay governor — how `algo.replay_ratio` converts
environment steps into gradient steps over time (reference parity:
examples/ratio.py; the law is Hafner's, pinned to the reference in
tests/test_regression/test_reference_fixture.py::test_ratio_matches_reference).

Usage:
    python examples/ratio.py [replay_ratio] [num_envs] [rollout_len]
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from sheeprl_tpu.utils.utils import Ratio


def main(argv) -> None:
    replay_ratio = float(argv[0]) if argv else 0.5
    num_envs = int(argv[1]) if len(argv) > 1 else 4
    rollout = int(argv[2]) if len(argv) > 2 else 16

    r = Ratio(replay_ratio)
    policy_steps = 0
    total_updates = 0
    print(f"replay_ratio={replay_ratio}  num_envs={num_envs}  rollout={rollout}\n")
    print(f"{'iteration':>9} {'policy_steps':>12} {'updates_now':>11} {'total_updates':>13} {'real_ratio':>10}")
    for it in range(1, 11):
        policy_steps += num_envs * rollout
        updates = r(policy_steps)
        total_updates += updates
        print(
            f"{it:>9} {policy_steps:>12} {updates:>11} {total_updates:>13} "
            f"{total_updates / policy_steps:>10.4f}"
        )
    print("\nThe realized ratio converges to replay_ratio; fractional remainders")
    print("carry between iterations instead of being dropped.")


if __name__ == "__main__":
    main(sys.argv[1:])
